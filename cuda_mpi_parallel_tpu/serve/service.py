"""The in-process solver service: register once, submit many.

``SolverService`` is the request-queue front end of the many-RHS tier
(ROADMAP item 1b): production traffic is repeat ``(matrix, b)``
requests against a small set of operators, and every RHS column that
rides an already-paid matrix sweep is nearly free (SpMV throughput is
sustained stream bandwidth, arXiv 2204.00900).  The service converts
arrival patterns into those batches:

* :meth:`SolverService.register` takes the operator ONCE - partitions,
  plans (``plan="auto"`` runs the balance planner a single time) and
  warms the compiled trace for every lane bucket - and returns an
  :class:`OperatorHandle` keyed by the matrix fingerprint.  Repeat
  traffic on the handle never re-plans and, after warmup, never
  re-traces (the ``dist_cg`` solver cache keyed on the plan
  fingerprint + bucket shape serves every dispatch).
* :meth:`SolverService.submit` enqueues one right-hand side and
  returns a ``concurrent.futures.Future`` resolving to a typed
  :class:`RequestResult`.  The microbatch policy (``serve.queue``)
  cuts per-``(handle, dtype, tol-class)`` batches on ``max_batch``
  full or ``max_wait_s`` elapsed, pads to the compiled lane bucket,
  and dispatches onto ``solver.solve_many`` /
  ``parallel.solve_distributed_many``.
* Failures are isolated per lane: a STAGNATED/DIVERGED/MAXITER lane
  fails only its own request (``CGBatchResult`` carries per-lane
  status).  Deadlines surface as typed TIMEOUT results, never as
  worker exceptions.  Backpressure is a bounded queue
  (``serve.queue.QueueFull``).
* Multi-tenant overload protection (this PR): ``submit()`` takes
  ``tenant``/``slo_class`` tags; per-tenant token buckets
  (``serve.admission``) and the shed-before-collapse ladder (degrade
  tolerance -> defer ``bulk`` -> reject with ``retry_after_s``)
  answer sustained overload with typed ``ADMISSION_REJECTED`` results
  instead of a timeout storm, while the weighted-fair
  deficit-round-robin dispatcher (``serve.sched``) keeps a hot tenant
  from starving everyone else.  ``workers=N`` runs N dispatch threads
  over the one LRU'd compiled-solver cache.

Observability from day one: ``request_enqueued`` / ``batch_dispatch``
/ ``request_done`` events (the batch's events share the underlying
solve's ``solve_id``), queue-depth / occupancy / padding gauges, and
request-latency histograms with p50/p95/p99 export
(``telemetry.registry``).

Clocking: with the default config the service runs a worker thread on
the monotonic clock.  Passing ``ServiceConfig(clock=...)`` switches to
MANUAL mode - no thread, the policy advances only on :meth:`pump` -
which is how the tests drive every timing branch deterministically
with a fake clock.
"""
from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..solver.status import CGStatus
from .admission import (
    AdmissionConfig,
    AdmissionController,
    ShedConfig,
    ShedLadder,
)
from .queue import (
    Batch,
    MicroBatchQueue,
    QueuedRequest,
    QueueFull,
    bucket_sizes,
    tol_class,
)
from .sched import (
    BatchCostModel,
    SchedConfig,
    WeightedFairScheduler,
    class_table,
)

__all__ = [
    "OperatorHandle",
    "QueueFull",
    "RecyclePolicy",
    "RequestResult",
    "RetryPolicy",
    "ServiceClosed",
    "ServiceConfig",
    "SolverService",
]

#: request-latency histogram bounds: service traffic is sub-ms queueing
#: plus ms-scale batched solves, far below the solver-wide defaults
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 10.0, 60.0)


class ServiceClosed(RuntimeError):
    """submit() after close(): the service no longer accepts work."""


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded re-enqueue of failed requests (robustness PR).

    A lane that ends ``ERROR`` (the engine's fault) or ``BREAKDOWN``
    (the problem's fault - possibly a transient data corruption) is
    RE-ENQUEUED, not re-solved inline: it goes back through the
    microbatch queue with ``attempts + 1`` and a ``ready_t`` backoff
    gate of ``backoff_s * 2**(attempts - 1)`` seconds, so a retry
    storm cannot monopolize the dispatcher and retried lanes coalesce
    into fresh batches like any other traffic.  After ``max_retries``
    the original typed status stands - loud, never silent.
    """

    max_retries: int = 2
    backoff_s: float = 0.0
    statuses: Tuple[str, ...] = ("ERROR", "BREAKDOWN")

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got "
                             f"{self.max_retries}")
        if self.backoff_s < 0:
            raise ValueError(f"backoff_s must be >= 0, got "
                             f"{self.backoff_s}")

    def backoff_for(self, attempts: int) -> float:
        """Exponential backoff before dispatch attempt ``attempts + 1``
        (``attempts`` >= 1 completed)."""
        return self.backoff_s * (2.0 ** max(attempts - 1, 0))


@dataclasses.dataclass(frozen=True)
class RecyclePolicy:
    """Per-handle Krylov-subspace recycling (``solver.recycle``,
    ROADMAP item 2): harvest approximate extreme Ritz vectors from
    early live dispatches and deflate them from later ones, so repeat
    traffic on a handle gets measurably faster the longer the service
    runs.

    The schedule: the first live dispatch runs with the basis ring +
    stride-1 flight recorder and seeds the handle's ``RecycleSpace``;
    subsequent dispatches deflate with it AND keep harvesting
    (accumulating Rayleigh-Ritz refinement) until ``patience``
    consecutive harvests stop improving the mean live-lane iteration
    count by ``min_improvement`` - then the recorders drop off and
    dispatches run the pure deflated lane (``refresh_every > 0``
    re-opens one harvest round every that-many deflated dispatches).
    A lane that BREAKS DOWN under deflation drops the space
    defensively.  The space is also dropped when the dist_cg LRU
    evicts the handle's compiled solvers (it rides the cache).
    """

    k: int = 8
    #: basis-ring rows; None sizes to the handle's maxiter (bounded by
    #: recycle.BASIS_CAPACITY_LIMIT)
    capacity: Optional[int] = None
    #: an accumulation round must cut mean live-lane iterations by at
    #: least this to count as improving
    min_improvement: float = 0.5
    #: consecutive non-improving harvests before the recorders drop
    patience: int = 2
    #: 0 = never re-open harvesting once frozen; N > 0 = one harvest
    #: round every N deflated dispatches (drift refresh)
    refresh_every: int = 0

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {self.k}")
        if self.patience < 1:
            raise ValueError(
                f"patience must be >= 1, got {self.patience}")
        if self.refresh_every < 0:
            raise ValueError(
                f"refresh_every must be >= 0, got {self.refresh_every}")


@dataclasses.dataclass
class _Breaker:
    """Per-handle circuit-breaker state (see ServiceConfig)."""

    state: str = "closed"           # closed | open | half_open
    consecutive_failures: int = 0
    opened_t: float = 0.0
    probing: bool = False           # half_open: one probe in flight
    probe_id: Optional[str] = None  # the probe request's id (so a
    #                                 probe that never dispatches -
    #                                 deadline expiry, push failure -
    #                                 releases the slot instead of
    #                                 wedging the handle)


@dataclasses.dataclass(frozen=True)
class ServiceConfig:
    """Policy knobs of one :class:`SolverService`.

    ``clock=None`` (default) runs a worker thread on
    ``time.monotonic``; any callable switches the service to manual
    mode (no thread - tests drive :meth:`SolverService.pump` with a
    fake clock, so max_wait/deadline branches are deterministic).
    """

    max_batch: int = 8
    max_wait_s: float = 0.002
    queue_limit: int = 256
    maxiter: int = 2000
    check_every: int = 1
    warm: bool = True
    clock: Optional[Callable[[], float]] = None
    #: bounded retry of ERROR/BREAKDOWN lanes (None = off): failed
    #: requests re-enqueue with exponential backoff, never re-solve
    #: inline
    retry: Optional[RetryPolicy] = None
    #: per-handle circuit breaker: this many CONSECUTIVE failed
    #: dispatches (every live lane ERROR/BREAKDOWN) opens the breaker
    #: - submits on the handle resolve immediately to typed REFUSED
    #: results until ``breaker_cooldown_s`` elapses, then ONE half-open
    #: probe is admitted (success closes, failure re-opens).  0 = off.
    breaker_threshold: int = 0
    breaker_cooldown_s: float = 1.0
    #: tolerance-class degradation under queue pressure: at total
    #: queue depth >= this, an incoming request's tolerance is relaxed
    #: one decade (tol * 10) and the result is marked ``degraded`` -
    #: the load-shedding step BEFORE backpressure rejects outright.
    #: 0 = off.
    degrade_depth: int = 0
    #: host-side finiteness check of every submitted b (robust
    #: pre-solve validation; False opts out for chaos staging)
    validate: bool = True
    #: Krylov-subspace recycling of repeat traffic (None = off): a
    #: per-handle RecycleSpace harvested from early dispatches and
    #: deflated from later ones (solver.recycle)
    recycle: Optional[RecyclePolicy] = None
    #: multi-tenant scheduling (serve.sched): SLO-class table +
    #: weighted-fair (deficit-round-robin) dispatch across
    #: (handle, tenant, class) flows.  None = the default SchedConfig
    #: (fair dispatch, gold/silver/bulk at 8:4:1);
    #: SchedConfig(fair=False) keeps the literal PR 10
    #: oldest-queue-first pop as the bit-for-bit reference.
    sched: Optional[SchedConfig] = None
    #: per-tenant token-bucket admission control (serve.admission):
    #: None = every tenant unmetered.  A rejected submit resolves to a
    #: typed ADMISSION_REJECTED result with a retry_after_s hint -
    #: never an exception
    admission: Optional[AdmissionConfig] = None
    #: the shed-before-collapse ladder (serve.admission.ShedConfig):
    #: degrade tolerance -> defer bulk -> reject at admission, driven
    #: by queue depth vs the measured capacity estimate.  None keeps
    #: only the legacy ``degrade_depth`` rung below
    shed: Optional[ShedConfig] = None
    #: dispatch workers in threaded mode (manual/fake-clock mode stays
    #: single-stepped by pump()).  1 = the PR 10 single worker;
    #: N > 1 = N workers sharing the one LRU'd compiled-solver cache;
    #: 0 = auto-size from the calibrated machine model
    #: (``calibrate.preferred_model``: a confidently-calibrated host
    #: is trusted to overlap one extra dispatcher, an uncalibrated
    #: one stays serialized)
    workers: int = 1
    #: rolling-window SLO burn-rate accounting per (tenant, slo_class)
    #: (telemetry.slo.SLOConfig; None = off).  Observe-only: every
    #: terminal outcome - completion, TIMEOUT, REFUSED,
    #: ADMISSION_REJECTED - lands in the tracker on the SERVICE clock
    #: (fake-clock drivable), gauges + typed ``slo_burn`` events ride
    #: the registry/event stream, and ``SLOTracker.burn_rate`` is the
    #: documented hook a future shed-ladder rung may consume
    slo: Optional[object] = None
    #: metered per-tenant usage attribution (serve.usage.UsageLedger;
    #: False = off): every dispatched batch's device-seconds, batch
    #: iterations and wire bytes are apportioned across the lanes that
    #: shared it, with the per-tenant sums reconciling against the
    #: batch totals (the billing substrate the network front end
    #: needs).  Host-side post-solve bookkeeping only
    usage: bool = False
    #: per-device HBM bytes a registered mesh handle's WORST bucket
    #: (``max_batch`` lanes wide) must fit in, by the
    #: ``telemetry.memscope`` static model (None = no gate).  An
    #: over-budget register raises ``MemoryBudgetError`` BEFORE any
    #: partition or compile, naming the bytes and the smallest mesh
    #: that would fit - capacity refusal belongs at registration, not
    #: as a device OOM under live traffic
    hbm_budget: Optional[float] = None
    #: per-batch dispatch log retained for reports (ring, drop-oldest)
    keep_batch_log: int = 1024
    #: exact latency samples retained for stats() percentiles (ring,
    #: drop-oldest - a long-running service must not grow one float
    #: per request forever; the registry histogram keeps the full
    #: cumulative story for scrapes)
    keep_latency_samples: int = 8192
    #: network ops plane (serve.ops; None = off): bind a read-only
    #: stdlib HTTP observatory on this port at construction -
    #: /metrics, /healthz, /readyz, /stats, /usage, /traces/<id>,
    #: /events (SSE).  0 = ephemeral port (tests read it off
    #: ``service.ops_server().port``).  Host-side reads only: a
    #: concurrent scrape never perturbs the solve stream
    ops_port: Optional[int] = None
    ops_host: str = "127.0.0.1"
    #: optional static bearer token gating every ops route (401
    #: without ``Authorization: Bearer <token>``)
    ops_token: Optional[str] = None

    #: the network DATA plane (serve.net): POST /v1/submit,
    #: POST /v1/solve, GET /v1/result/<id>, GET /v1/stream (SSE),
    #: GET /v1/handles.  0 = ephemeral port (tests read it off
    #: ``service.net_server().port``).  Requires ``net_keyring``:
    #: every submit authenticates a bearer token whose keyring entry
    #: DERIVES the tenant tag - the body can cross-check but never
    #: claim someone else's (serve.auth)
    net_port: Optional[int] = None
    net_host: str = "127.0.0.1"
    #: serve.auth.TokenKeyring mapping bearer token -> TenantIdentity;
    #: mandatory when the data plane is on (an unauthenticated data
    #: plane would reopen the tenant-spoofing hole this closes)
    net_keyring: Optional[object] = None


@dataclasses.dataclass(frozen=True)
class RequestResult:
    """The typed terminal outcome of one submitted right-hand side.

    ``status`` is a ``CGStatus`` name (per-lane, so one failing lane
    never contaminates its batchmates), ``"TIMEOUT"`` for a deadline
    expiry (the request was never dispatched), ``"REFUSED"`` when the
    handle's circuit breaker was open, or ``"ERROR"`` when the
    batch's engine call itself raised (still a typed RESULT - a future
    never raises, so ``fut.result()`` loops survive any failure mode;
    the exception text rides the ``request_done`` event).

    ``"BREAKDOWN"`` is deliberately distinct from ``"ERROR"``: a
    breakdown is the *problem's* fault (non-finite recurrence - bad
    data, a poisoned halo payload, a non-SPD preconditioner; see
    ``CGStatus.BREAKDOWN.describe()``), an ERROR is the *engine's*
    (the dispatch itself raised).  :attr:`failure_kind` names the
    class; the retry policy treats both as retryable, the circuit
    breaker counts both.

    ``solve_s`` is the batch's wall time - shared by every lane that
    rode it; ``latency_s = wait_s + solve_s`` is what the service's
    latency histogram records.  ``attempts`` counts completed dispatch
    attempts (> 1 = the retry policy re-enqueued it); ``degraded``
    marks a tolerance relaxed under queue pressure.
    """

    request_id: str
    status: str
    converged: bool
    timed_out: bool
    x: Optional[np.ndarray]
    iterations: int
    residual_norm: float
    wait_s: float
    solve_s: float
    latency_s: float
    bucket: int
    occupancy: float
    solve_id: Optional[str]
    attempts: int = 1
    degraded: bool = False
    #: multi-tenant scheduling: the submitting tenant and SLO class
    tenant: str = "default"
    slo_class: str = "silver"
    #: ADMISSION_REJECTED only: when the admission controller suggests
    #: retrying (token-bucket refill / estimated backlog drain time)
    retry_after_s: Optional[float] = None

    @property
    def ok(self) -> bool:
        return self.converged and not self.timed_out

    @property
    def failure_kind(self) -> Optional[str]:
        """``"problem"`` (BREAKDOWN - the system's fault), ``"engine"``
        (ERROR - the dispatch raised), ``"deadline"`` (TIMEOUT),
        ``"breaker"`` (REFUSED), ``"admission"``
        (ADMISSION_REJECTED - the tenant's rate or the shed ladder),
        ``"budget"``/``"convergence"`` for MAXITER/STAGNATED/DIVERGED,
        or ``None`` when converged."""
        return {
            "BREAKDOWN": "problem",
            "ERROR": "engine",
            "TIMEOUT": "deadline",
            "REFUSED": "breaker",
            "ADMISSION_REJECTED": "admission",
            "MAXITER": "budget",
            "STAGNATED": "convergence",
            "DIVERGED": "convergence",
        }.get(self.status)


@dataclasses.dataclass
class OperatorHandle:
    """One registered operator: everything a dispatch needs, resolved
    once at registration (plan, preconditioner, exchange lane, lane
    buckets, and - on a mesh - the partition-once
    ``parallel.ManyRHSDispatcher``).  ``key`` - matrix fingerprint +
    config digest - is the queue key; two registrations of the same
    matrix under the same config return the SAME handle."""

    key: str
    fingerprint: str
    a: object
    n: int
    dtype_name: str
    mesh: Optional[object]
    plan: Optional[object]
    exchange: Optional[str]
    precond: Optional[str]
    precond_obj: Optional[object]
    method: str
    maxiter: int
    check_every: int
    buckets: Tuple[int, ...]
    #: mesh handles only: the prepared partition + sharded matrix
    #: arrays, so a dispatch's host work is padding/sharding b
    dispatcher: Optional[object] = None
    #: every lane bucket's trace has been compiled (register warmup);
    #: a deferred-warm handle flips this when a later register() (or
    #: explicit warm) pays the compiles
    warmed: bool = False
    #: measured phase profile of the handle's partition
    #: (telemetry.phasetrace.PhaseProfile), taken at registration when
    #: register(phase_profile=R) asked for one - rides the handle so
    #: reports/CLI can render it without re-measuring
    phase_profile: Optional[object] = None
    #: armed chaos fault (robust.FaultPlan) baked into every dispatch
    #: of this handle - the test harness's "poisoned handle" (drives
    #: the retry/breaker drills deterministically)
    inject: Optional[object] = None
    #: Krylov recycling state (ServiceConfig.recycle): the harvested
    #: solver.recycle.RecycleSpace consulted by later dispatches, its
    #: HarvestInfo, and the quality-schedule counters
    recycle_space: Optional[object] = None
    recycle_info: Optional[object] = None
    #: mean live-lane iterations of the handle's FIRST harvest-source
    #: dispatch (the undeflated baseline iters-saved is measured
    #: against)
    recycle_baseline_iters: Optional[float] = None
    recycle_best_iters: Optional[float] = None
    recycle_stale: int = 0
    recycle_frozen: bool = False
    recycle_deflated_since_harvest: int = 0
    recycle_harvests: int = 0

    @property
    def distributed(self) -> bool:
        return self.mesh is not None


def _matrix_fingerprint(a) -> str:
    """Stable digest of an operator's mathematical IDENTITY - the
    handle key component that makes repeat traffic on the same matrix
    land on the same compiled state, whatever kernel backend built the
    operator object.  One hashing scheme repo-wide: the checkpoint
    module's (explicit field walk, never ``str(treedef)``)."""
    from ..utils.checkpoint import operator_fingerprint

    return operator_fingerprint(a)[:12]


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of an already-sorted list (exact, for
    the service's own report; the registry histogram's interpolated
    readout serves scrapes)."""
    if not sorted_vals:
        return None
    idx = max(0, int(np.ceil(q * len(sorted_vals))) - 1)
    return float(sorted_vals[idx])


class SolverService:
    """See the module docstring.  One service hosts many operators;
    each batch dispatch serves exactly one handle."""

    def __init__(self, config: Optional[ServiceConfig] = None):
        self.config = config or ServiceConfig()
        self._clock = self.config.clock or time.monotonic
        self._manual = self.config.clock is not None
        # multi-tenant scheduling: the SLO-class table, the priced
        # cost model, and (unless fair=False keeps the PR 10 pop) the
        # deficit-round-robin scheduler the queue consults
        self._sched_cfg = self.config.sched or SchedConfig()
        self._classes = class_table(self._sched_cfg.classes)
        self._cost_model = BatchCostModel()
        sched = WeightedFairScheduler(self._sched_cfg) \
            if self._sched_cfg.fair else None
        self._queue = MicroBatchQueue(
            max_batch=self.config.max_batch,
            max_wait_s=self.config.max_wait_s,
            queue_limit=self.config.queue_limit,
            sched=sched, cost_fn=self._cost_model.price)
        # admission + shed ladder (serve.admission).  A bare legacy
        # degrade_depth maps onto the ladder's first rung, so PR 12
        # configs keep their exact behavior
        self._admission = AdmissionController(self.config.admission) \
            if self.config.admission is not None else None
        shed_cfg = self.config.shed
        if shed_cfg is None:
            shed_cfg = ShedConfig(
                degrade_depth=max(int(self.config.degrade_depth), 0))
        elif self.config.degrade_depth > 0 \
                and shed_cfg.degrade_depth == 0 and not shed_cfg.auto:
            raise ValueError(
                "both ServiceConfig.shed and the legacy degrade_depth "
                "are set but the ShedConfig's degrade rung is off - "
                "put the depth in ShedConfig.degrade_depth (one knob, "
                "no silent precedence)")
        self._shed = ShedLadder(shed_cfg)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._handles: Dict[str, OperatorHandle] = {}
        self._ids = itertools.count(1)
        self._closed = False
        self._stop = False
        # host-side tallies behind the metrics (exact, for stats())
        self._submitted = 0
        self._completed = 0
        self._timeouts = 0
        self._errors = 0
        self._converged = 0
        self._n_batches = 0
        self._lane_total = 0
        self._padded_lanes = 0
        self._occupancy_sum = 0.0
        self._bucket_counts: Dict[int, int] = {}
        self._retries = 0
        self._refused = 0
        self._degraded = 0
        self._migrations = 0
        self._admission_rejected = 0
        self._deferred = 0
        # per-tenant / per-SLO-class tallies (exact, for stats())
        self._tenant_stats: Dict[str, Dict[str, int]] = {}
        self._class_stats: Dict[str, Dict[str, int]] = {}
        self._class_latencies: Dict[str, deque] = {}
        # measured capacity estimate: EWMA of solved RHS/s over
        # dispatched batches (live lanes / solve wall), seeded at
        # registration from the phase profile when one was taken -
        # what the shed ladder's auto thresholds price against
        self._rate_ewma: Optional[float] = None
        self._rate_seed: Optional[float] = None
        # defer-note throttle: one sched_dispatch decision="defer"
        # event per held flow per ladder episode (reset on level
        # change), so a long hold does not flood the trace
        self._defer_noted: set = set()
        self._breakers: Dict[str, _Breaker] = {}
        # request observatory: rolling SLO burn accounting and the
        # per-tenant usage ledger (both None/off by default - the
        # observe paths below stay untouched)
        self._slo = None
        if self.config.slo is not None:
            from ..telemetry.slo import SLOConfig, SLOTracker

            if not isinstance(self.config.slo, SLOConfig):
                raise TypeError(
                    f"ServiceConfig.slo must be a telemetry.slo."
                    f"SLOConfig, got "
                    f"{type(self.config.slo).__name__}")
            self._slo = SLOTracker(self.config.slo)
        self._usage = None
        if self.config.usage:
            from .usage import UsageLedger

            self._usage = UsageLedger()
        self._latencies: deque = deque(
            maxlen=self.config.keep_latency_samples)
        # the wait-vs-solve split of the same completions: queueing
        # delay and batched solve wall answer different tuning
        # questions (max_wait/max_batch vs operator/bucket), so
        # stats() reports their percentiles separately
        self._waits: deque = deque(
            maxlen=self.config.keep_latency_samples)
        self._solves: deque = deque(
            maxlen=self.config.keep_latency_samples)
        self._batch_log: deque = deque(maxlen=self.config.keep_batch_log)
        # Krylov recycling bookkeeping (ServiceConfig.recycle)
        self._recycle_harvests = 0
        self._recycle_applied = 0
        self._recycle_dropped = 0
        self._recycle_first_iters: Optional[float] = None
        self._recycle_last_iters: Optional[float] = None
        self._evict_listener = None
        if self.config.recycle is not None:
            from ..parallel import dist_cg

            # the per-handle space rides the compiled-solver LRU: when
            # a handle's solvers are evicted, its space goes with them
            self._evict_listener = self._on_solver_evicted
            dist_cg.add_evict_listener(self._evict_listener)
        # single-dispatcher serialization (manual pumps, drain, and
        # the workers == 1 thread): one engine call at a time.  A
        # multi-worker pool (workers > 1) deliberately skips this lock
        # - concurrent dispatch onto the shared compiled-solver cache
        # is the point - and quiescence is proven by the in-flight
        # counter instead
        self._dispatch_lock = threading.Lock()
        self._inflight = 0
        self._n_workers = self._resolve_workers()
        if self.config.recycle is not None and self._n_workers > 1:
            raise ValueError(
                "ServiceConfig.recycle with workers > 1 is "
                "unsupported: the per-handle harvest schedule is a "
                "serial accumulation (concurrent dispatches would "
                "race the basis ring); run recycling on one worker")
        self._workers: List[threading.Thread] = []
        if not self._manual:
            for i in range(self._n_workers):
                t = threading.Thread(
                    target=self._worker_loop,
                    name=f"cuda-mpi-parallel-tpu-serve-{i}",
                    daemon=True)
                t.start()
                self._workers.append(t)
        # the network ops plane (serve.ops) - read-only HTTP
        # observatory, torn down by close()
        self._ops_server = None
        if self.config.ops_port is not None:
            self.serve_ops(self.config.ops_port,
                           host=self.config.ops_host,
                           token=self.config.ops_token)
        # the network data plane (serve.net) - authenticated
        # submit/result RPC, torn down by close()
        self._net_server = None
        if self.config.net_port is not None:
            self.serve_net(self.config.net_port,
                           host=self.config.net_host,
                           keyring=self.config.net_keyring)

    def _resolve_workers(self) -> int:
        """``config.workers``, with 0 = auto-size from the calibrated
        machine model: a host whose calibration cache holds a
        confident measured fit gets one extra dispatcher to overlap
        host-side batch prep with the device solve; an uncalibrated
        host stays at the PR 10 single worker (no guessing)."""
        workers = int(self.config.workers)
        if workers < 0:
            raise ValueError(f"workers must be >= 0, got {workers}")
        if workers > 0:
            return workers
        try:
            from ..telemetry.calibrate import preferred_model

            return 2 if preferred_model() is not None else 1
        except Exception:
            return 1

    # -- registration ----------------------------------------------------

    def register(self, a, *, mesh=None, n_devices: Optional[int] = None,
                 plan=None, exchange: Optional[str] = None,
                 precond: Optional[str] = None, method: str = "batched",
                 maxiter: Optional[int] = None,
                 check_every: Optional[int] = None,
                 warm: Optional[bool] = None,
                 phase_profile: int = 0,
                 inject=None) -> OperatorHandle:
        """Register an operator: resolve the plan, build the
        preconditioner, and (by default) warm the compiled trace of
        EVERY lane bucket so later traffic only ever hits caches.

        Single-device (``mesh=None``, ``n_devices=None``) accepts any
        ``LinearOperator``; a mesh accepts assembled ``CSRMatrix``
        problems on a 1-D mesh with ``precond`` ``None``/``"jacobi"``
        (the scope of ``solve_distributed_many`` - anything else
        refuses here, at registration, not per request).  Re-registering
        the same matrix under the same config returns the same handle
        without re-warming.

        ``inject`` arms a ``robust.FaultPlan`` into every dispatch of
        the handle (the chaos harness's "poisoned handle" - what the
        retry/breaker drills register).  The fault fires in-trace at
        its configured iteration; ``None`` leaves the compiled solve
        untouched.

        ``phase_profile=R > 0`` (mesh handles only) additionally runs
        the measured phase profiler (``telemetry.phasetrace``, ``R``
        chained reps per phase) against the handle's own partition at
        registration - alongside warmup, never inside request latency -
        and parks the :class:`~..telemetry.phasetrace.PhaseProfile` on
        ``handle.phase_profile`` (also emitted as a ``phase_profile``
        event + gauges).
        """
        from ..models.operators import LinearOperator
        from ..solver.cg import _as_operator
        from ..solver.many import MANY_METHODS

        if method not in MANY_METHODS:
            raise ValueError(f"unknown method {method!r}; expected one "
                             f"of {MANY_METHODS}")
        if self.config.recycle is not None:
            # refuse at REGISTRATION, not silently per dispatch: the
            # recycling schedule rides the batched recurrence's basis
            # ring/deflation lane, and a poisoned handle must not
            # harvest a poisoned spectrum
            if method != "batched":
                raise ValueError(
                    "ServiceConfig.recycle needs method='batched' "
                    "handles (block-CG deflates rank collapse in-lane "
                    "and carries no per-lane Lanczos harvest); "
                    "register with method='batched' or drop the "
                    "recycle policy")
            if inject is not None:
                raise ValueError(
                    "ServiceConfig.recycle with inject= is "
                    "unsupported (a chaos-poisoned handle must not "
                    "harvest - and deflation would mask the armed "
                    "fault)")
        if precond not in (None, "jacobi"):
            raise ValueError(
                f"the solver service supports precond None or 'jacobi' "
                f"(got {precond!r}); heavier preconditioners are "
                f"single-vector per application and do not batch")
        if not isinstance(a, LinearOperator):
            a = _as_operator(a)
        if mesh is None and n_devices is not None:
            from ..parallel.mesh import make_mesh

            mesh = make_mesh(n_devices)
        if mesh is not None:
            from jax.sharding import Mesh

            if not isinstance(mesh, Mesh):
                raise TypeError(f"mesh must be a jax.sharding.Mesh, "
                                f"got {type(mesh).__name__}")
        else:
            if exchange is not None:
                raise ValueError("exchange= needs a mesh (it is the "
                                 "distributed halo wire)")
            if plan is not None:
                raise ValueError("plan= needs a mesh (partition "
                                 "planning rebalances a device mesh)")
            if phase_profile:
                raise ValueError(
                    "phase_profile= needs a mesh (the profiler times "
                    "the distributed halo/spmv/reduction phases)")
        if phase_profile < 0:
            raise ValueError(
                f"phase_profile must be >= 0, got {phase_profile}")

        # dedup BEFORE any O(nnz) construction: the key hashes the
        # REQUESTED plan spec ("auto"/None/a plan's fingerprint), so a
        # re-register of the same matrix under the same config returns
        # the existing handle without re-planning or re-partitioning
        fingerprint = _matrix_fingerprint(a)
        plan_spec = plan.fingerprint() \
            if callable(getattr(plan, "fingerprint", None)) \
            else repr(plan)
        cfg = hashlib.sha1(repr((
            None if mesh is None else tuple(mesh.devices.shape),
            plan_spec, exchange, precond, method,
            maxiter or self.config.maxiter,
            check_every or self.config.check_every,
            self.config.max_batch,
            inject.fingerprint() if inject is not None else None,
        )).encode()).hexdigest()[:8]
        key = f"{fingerprint}:{cfg}"
        want_warm = self.config.warm if warm is None else warm
        with self._lock:
            existing = self._handles.get(key)
        if existing is not None:
            # dedup must not silently skip a warmup the caller asked
            # for: a handle first registered warm=False gets its
            # buckets compiled by the first warm=True re-register
            # (otherwise live traffic would pay the compiles and trip
            # the zero-post-warmup-miss monitoring)
            if want_warm and not existing.warmed:
                self._warm(existing)
                existing.warmed = True
            # same rule for a requested phase profile: measure it on
            # the dedup hit if the handle does not carry one yet
            if phase_profile and existing.phase_profile is None:
                existing.phase_profile = self._phase_profile(
                    existing, int(phase_profile))
                self._seed_capacity(existing)
            return existing

        dispatcher = None
        if mesh is not None:
            from ..parallel.dist_cg import ManyRHSDispatcher

            self._check_memory_budget(a, mesh, exchange)
            # the partition-once half of solve_distributed_many:
            # validates the mesh/operator/exchange combination, resolves
            # the plan (plan="auto" runs the planner HERE, exactly
            # once), permutes + partitions + shards the matrix arrays
            dispatcher = ManyRHSDispatcher(
                a, mesh=mesh,
                maxiter=int(maxiter or self.config.maxiter),
                preconditioner=precond, method=method,
                check_every=int(check_every or self.config.check_every),
                plan=plan, exchange=exchange, inject=inject)
            plan = dispatcher.plan
        precond_obj = None
        if precond == "jacobi" and mesh is None:
            from ..models.operators import JacobiPreconditioner

            precond_obj = JacobiPreconditioner.from_operator(a)
        dtype_name = np.dtype(a.dtype).name
        if not np.issubdtype(np.dtype(dtype_name), np.floating):
            dtype_name = np.dtype(np.result_type(float)).name
        handle = OperatorHandle(
            key=key, fingerprint=fingerprint, a=a, n=int(a.shape[0]),
            dtype_name=dtype_name, mesh=mesh, plan=plan,
            exchange=exchange, precond=precond,
            precond_obj=precond_obj, method=method,
            maxiter=int(maxiter or self.config.maxiter),
            check_every=int(check_every or self.config.check_every),
            buckets=bucket_sizes(self.config.max_batch),
            dispatcher=dispatcher, inject=inject)
        with self._lock:
            self._handles[key] = handle
            n_handles = len(self._handles)
        from ..telemetry.registry import REGISTRY

        REGISTRY.gauge("serve_registered_operators",
                       "operators registered with the solver "
                       "service").set(n_handles)
        if want_warm:
            self._warm(handle)
            handle.warmed = True
        if phase_profile:
            handle.phase_profile = self._phase_profile(
                handle, int(phase_profile))
            self._seed_capacity(handle)
        return handle

    def _check_memory_budget(self, a, mesh, exchange) -> None:
        """Predict the registering handle's per-device footprint at its
        WIDEST bucket (``max_batch`` lanes) and refuse OVERFLOW before
        any partition work or compile (``ServiceConfig.hbm_budget``;
        None = no gate, but the prediction is still parked/emitted as a
        ``memory_profile`` event for observability).

        The model prices the allgather extended-x buffer - the upper
        bound of every batched exchange lane (a gather schedule's halo
        slab is never wider than the full remote block) - so a FITS
        verdict here holds for whichever lane the planner picks."""
        from ..telemetry import memscope

        indptr = getattr(a, "indptr", None)
        if indptr is None:
            return     # matrix-free operators never reach the mesh path
        budget = self.config.hbm_budget
        n = int(a.shape[0])
        n_shards = int(mesh.devices.size)
        itemsize = int(np.asarray(a.data).dtype.itemsize)
        k = int(self.config.max_batch)
        fp = memscope.predict_footprint(
            n=n, n_shards=n_shards, indptr=np.asarray(indptr),
            itemsize=itemsize, n_rhs=k, exchange="allgather",
            hbm_bytes=budget if budget is not None else "auto")
        if budget is not None and fp.classification == "OVERFLOW":
            fit = memscope.smallest_fitting_mesh(
                n=n, budget_bytes=budget, indptr=np.asarray(indptr),
                itemsize=itemsize, n_rhs=k, exchange="allgather",
                start=n_shards)
            hint = (f"; the smallest mesh that fits is {fit} shards"
                    if fit is not None else
                    "; no mesh size fits (the k-wide vector stack "
                    "alone exceeds the budget - lower max_batch)")
            raise memscope.MemoryBudgetError(
                f"registering this {n}-row operator on {n_shards} "
                f"shard(s) needs {int(fp.peak_bytes)} bytes/device at "
                f"max_batch={k} but hbm_budget is {int(budget)}{hint}",
                required_bytes=int(fp.peak_bytes),
                budget_bytes=int(budget), n_shards=n_shards,
                smallest_fitting_mesh=fit)
        memscope.note_footprint(fp)

    def _seed_capacity(self, handle: OperatorHandle) -> None:
        """Seed the shed ladder's capacity estimate from the measured
        phase profile: a full bucket over a worst-case solve
        (``step_s`` x maxiter) - deliberately pessimistic, and dead
        the moment the first real dispatch lands in the EWMA."""
        profile = handle.phase_profile
        if profile is None:
            return
        step_s = float(getattr(profile, "step_s", 0.0))
        if step_s <= 0:
            return
        seed = self.config.max_batch / (step_s * max(handle.maxiter, 1))
        with self._lock:
            self._rate_seed = seed if self._rate_seed is None \
                else min(self._rate_seed, seed)

    def _phase_profile(self, handle: OperatorHandle, repeats: int):
        """Measure the handle's phase profile on its OWN partition (the
        dispatcher's parts - the arrays every later dispatch runs).
        Registration-time only: the profiler's dispatches must never
        ride inside request latency."""
        from ..telemetry import phasetrace

        profile = phasetrace.profile_partition(
            handle.dispatcher.parts, handle.mesh, repeats=repeats,
            plan=(handle.plan.label if handle.plan is not None
                  else "even"))
        return phasetrace.note_profile(profile)

    def _warm(self, handle: OperatorHandle) -> None:
        """Compile every lane bucket ONCE, before traffic: a zero-RHS
        stack freezes every lane at iteration 0 (``stack_columns``
        docstring), so each warmup pays the trace + compile and almost
        nothing else.  Warmup events carry ``phase="warmup"`` - the
        zero-retrace acceptance counts cache misses OUTSIDE this
        scope."""
        from ..telemetry import events

        for k in handle.buckets:
            b0 = np.zeros((handle.n, k),
                          dtype=np.dtype(handle.dtype_name))
            tol0 = np.full((k,), 1e-7,
                           dtype=np.dtype(handle.dtype_name))
            with events.scoped(phase="warmup"):
                with events.solve_scope():
                    res = self._engine(handle, b0, tol0)
            np.asarray(res.x)   # block: the compile is really done

    def migrate(self, handle: OperatorHandle, *, mesh=None,
                n_devices: Optional[int] = None) -> OperatorHandle:
        """Move a LIVE mesh handle onto a new mesh shape - the serving
        half of elastic solves (a host reclaim shrank the pod, or the
        watchdog flagged a shard).

        The new ``parallel.ManyRHSDispatcher`` is built and every lane
        bucket re-warmed OFF the request path (warmup-scoped events,
        exactly like registration) before the handle is swapped, so
        live traffic never pays a compile.  Queued requests are
        PRESERVED - they reference the handle, not the dispatcher, and
        dispatch on the new mesh after the swap (zero drops); a batch
        already in flight finishes on the dispatcher it started with
        (the swap serializes behind the dispatch lock in single-worker
        mode).  The handle's ``RecycleSpace`` is dropped defensively -
        a space harvested under the old layout deflates the same
        GLOBAL vectors, but the conservative contract is re-harvest on
        the new mesh rather than trust the seam.  ``plan="auto"``
        handles re-plan for the new shard count (calibrated machine
        model when one exists); even-split handles stay even.

        Emits a ``handle_migrated`` event; the handle object (and its
        key) is unchanged, so held references keep working.
        """
        from jax.sharding import Mesh

        from ..parallel.dist_cg import ManyRHSDispatcher
        from ..parallel.mesh import make_mesh
        from ..telemetry import events
        from ..telemetry.registry import REGISTRY

        with self._lock:
            if self._handles.get(handle.key) is not handle:
                raise ValueError(
                    "unknown handle (register the operator with THIS "
                    "service first)")
        if not handle.distributed:
            raise ValueError(
                "migrate() moves MESH handles between mesh shapes; "
                "this handle is single-device (re-register with "
                "mesh=/n_devices= instead)")
        if mesh is None:
            if n_devices is None:
                raise ValueError("migrate() needs mesh= or n_devices=")
            mesh = make_mesh(n_devices)
        if not isinstance(mesh, Mesh):
            raise TypeError(f"mesh must be a jax.sharding.Mesh, got "
                            f"{type(mesh).__name__}")
        n_from = int(handle.mesh.devices.size)
        n_to = int(mesh.devices.size)

        # build + warm the new dispatcher entirely off the request
        # path: queued traffic keeps dispatching on the old mesh until
        # the swap below
        dispatcher = ManyRHSDispatcher(
            handle.a, mesh=mesh, maxiter=handle.maxiter,
            preconditioner=handle.precond, method=handle.method,
            check_every=handle.check_every,
            plan=("auto" if handle.plan is not None else None),
            exchange=handle.exchange, inject=handle.inject)
        for k in handle.buckets:
            b0 = np.zeros((handle.n, k),
                          dtype=np.dtype(handle.dtype_name))
            tol0 = np.full((k,), 1e-7,
                           dtype=np.dtype(handle.dtype_name))
            with events.scoped(phase="warmup"):
                with events.solve_scope():
                    res = dispatcher.solve(b0, tol=tol0)
            np.asarray(res.x)   # block: the compile is really done

        # the swap: behind the dispatch lock so a single-worker batch
        # in flight finishes on the dispatcher it started with; queued
        # requests reference the HANDLE and ride the new mesh from the
        # next pop (zero drops)
        with self._dispatch_lock:
            with self._lock:
                handle.mesh = mesh
                handle.dispatcher = dispatcher
                handle.plan = dispatcher.plan
                self._migrations += 1
                affected = self._queue.pending_requests(handle.key)
        # the mesh swap is a causal fact of every queued request's
        # life: their next dispatch runs on the new layout, so each
        # live trace gets a migration span (chained, so the following
        # queue_wait/solve spans hang off it)
        t_migrated = self._clock()
        for req in affected:
            if req.trace is not None:
                req.trace.span("migration", start_s=t_migrated,
                               duration_s=0.0, handle=handle.key,
                               n_shards_from=n_from,
                               n_shards_to=n_to)
        if handle.recycle_space is not None:
            # defensive: re-harvest on the new layout rather than
            # trust a space across the seam
            self._drop_recycle_space(handle)
        REGISTRY.counter(
            "serve_handles_migrated_total",
            "live operator handles migrated to a new mesh shape",
            labelnames=("handle",)).inc(handle=handle.key)
        events.emit("handle_migrated", handle=handle.key,
                    n_shards_from=n_from, n_shards_to=n_to,
                    plan=(handle.plan.label if handle.plan is not None
                          else "even"))
        return handle

    # -- submission ------------------------------------------------------

    def submit(self, handle: OperatorHandle, b, *, tol: float = 1e-7,
               deadline_s: Optional[float] = None,
               tenant: str = "default",
               slo_class: str = "silver",
               net_hop: Optional[dict] = None) -> Future:
        """Enqueue one right-hand side; returns a Future resolving to
        a :class:`RequestResult`.  ``b`` is coerced to the handle's
        compiled dtype (the service trades that copy for a bounded
        compiled-shape set).  ``deadline_s`` is relative to now (a
        ``None`` takes the SLO class's default, if it declares one);
        an expired request resolves to a typed TIMEOUT result.

        ``tenant``/``slo_class`` tag the request for admission control
        and weighted-fair dispatch: a tenant past its token-bucket
        rate - or any non-gold submit while the shed ladder's reject
        rung holds - resolves immediately to a typed
        ``ADMISSION_REJECTED`` result with a ``retry_after_s`` hint.
        Raises :class:`ServiceClosed` after close() and
        :class:`serve.queue.QueueFull` at the hard backpressure bound.

        ``net_hop`` (data plane only): timing/size fields of the HTTP
        hop that carried this submit; when tracing is live they become
        a ``"net"`` span under the request's root, so causal trees
        show the wire cost ahead of admission.
        """
        if handle.key not in self._handles:
            raise ValueError("unknown handle (register the operator "
                             "with THIS service first)")
        cls = self._classes.get(slo_class)
        if cls is None:
            raise ValueError(
                f"unknown SLO class {slo_class!r}; this service knows "
                f"{sorted(self._classes)}")
        b = np.asarray(b)
        if b.ndim != 1 or b.shape[0] != handle.n:
            raise ValueError(
                f"b must be 1-D of length {handle.n}, got shape "
                f"{b.shape} (submit one RHS per request - batching is "
                f"the service's job)")
        if self.config.validate:
            from ..robust.validate import check_finite_rhs

            check_finite_rhs(b, what="submitted b")
        b = np.ascontiguousarray(b, dtype=np.dtype(handle.dtype_name))
        tol = float(tol)
        if deadline_s is None:
            deadline_s = cls.deadline_s
        now = self._clock()
        # closed beats everything: a REFUSED future from an open
        # breaker must not mask the documented ServiceClosed contract
        # (and must not burn the half-open probe slot on a submission
        # that can never dispatch)
        with self._lock:
            if self._closed:
                raise ServiceClosed(
                    "solver service is closed (no new submissions)")
        rid = f"q{next(self._ids):06d}"
        from ..telemetry import events

        # the causal trace root: minted only when an event sink is
        # live, so the tracing-off submit path carries no trace state
        # at all (the jaxpr-bit-identity proof rides on this)
        trace = None
        if events.active():
            from ..telemetry.tracing import RequestTrace

            trace = RequestTrace(rid)
            trace.span("submit", start_s=now, duration_s=0.0,
                       root=True, handle=handle.key, tenant=tenant,
                       slo_class=slo_class)
            if net_hop:
                # the transport hop that carried this submit
                # (serve.net): receive+parse timing and wire size,
                # parented to the root so the causal tree shows the
                # network cost ahead of admission
                hop = dict(net_hop)
                hop_dur = float(hop.pop("duration_s", 0.0))
                hop_start = float(hop.pop("start_s", now - hop_dur))
                trace.span("net", start_s=hop_start,
                           duration_s=hop_dur, **hop)
        if self._breaker_refuses(handle.key, now, rid):
            return self._refuse(rid, handle, now, tenant, slo_class,
                                trace=trace)
        # the shed ladder, in order: reject (non-exempt classes
        # refused at the door with a retry hint) beats admission
        # metering beats degrade - every rung strictly milder than
        # letting accepted work time out
        level = self._evaluate_shed(now)
        if level >= 3 and not cls.reject_exempt:
            return self._admission_reject(
                rid, handle, tenant, slo_class,
                retry_after_s=self._drain_eta(), reason="shed",
                tokens=None, trace=trace)
        if self._admission is not None:
            with self._lock:
                decision = self._admission.admit(tenant, now)
            self._note_tokens(tenant, decision.tokens)
            if not decision.admitted:
                return self._admission_reject(
                    rid, handle, tenant, slo_class,
                    retry_after_s=decision.retry_after_s,
                    reason=decision.reason, tokens=decision.tokens,
                    trace=trace)
        degraded = False
        degrade_rung_on = self._shed.config.thresholds(
            self._capacity())[0] is not None
        if level >= 1 and cls.degrade_ok and degrade_rung_on:
            # the ladder's first rung (PR 12's degrade_depth,
            # generalized per class), cumulative with the rungs above
            # it but never fired when the operator disabled it: relax
            # the tolerance one decade so the queue drains faster; the
            # result says so (degraded=True), nothing is silent
            tol, degraded = tol * 10.0, True
        if trace is not None:
            trace.span("admission", start_s=now, duration_s=0.0,
                       decision="accepted", degraded=degraded,
                       shed_level=level)
        req = QueuedRequest(
            request_id=rid,
            handle_key=handle.key, b=b, dtype=handle.dtype_name,
            tol=tol, enqueue_t=now,
            deadline_t=(now + float(deadline_s)
                        if deadline_s is not None else None),
            future=Future(), handle=handle, degraded=degraded,
            tenant=tenant, slo_class=slo_class, trace=trace)
        try:
            with self._cond:
                if self._closed:
                    raise ServiceClosed(
                        "solver service is closed (no new "
                        "submissions)")
                depth = self._queue.push(req)      # raises QueueFull
                tenant_depth = \
                    self._queue.depth_by_tenant().get(tenant, 0)
                self._submitted += 1
                if degraded:
                    self._degraded += 1
                self._tenant_tally(tenant)["submitted"] += 1
                self._class_tally(slo_class)["submitted"] += 1
                self._cond.notify_all()
        except (QueueFull, ServiceClosed):
            # a probe that never made it into the queue releases its
            # slot - otherwise the handle would refuse forever
            self._breaker_release_probe(handle.key, rid)
            raise
        from ..telemetry import events
        from ..telemetry.registry import REGISTRY

        REGISTRY.counter("serve_requests_total",
                         "requests submitted to the solver service",
                         labelnames=("handle",)).inc(handle=handle.key)
        REGISTRY.gauge("serve_queue_depth",
                       "requests pending in the solver service "
                       "queues").set(depth)
        REGISTRY.gauge(
            "serve_tenant_queue_depth",
            "requests pending per tenant",
            labelnames=("tenant",)).set(tenant_depth, tenant=tenant)
        if degraded:
            REGISTRY.counter(
                "serve_degraded_total",
                "requests whose tolerance class was relaxed under "
                "queue pressure (load shedding)",
                labelnames=("handle",)).inc(handle=handle.key)
        events.emit("request_enqueued", request_id=req.request_id,
                    handle=handle.key, queue_depth=depth,
                    tol_class=tol_class(tol), degraded=degraded,
                    tenant=tenant, slo_class=slo_class)
        return req.future

    # -- multi-tenant bookkeeping / shed ladder --------------------------

    def _tenant_tally(self, tenant: str) -> Dict[str, int]:
        """Caller holds the lock."""
        return self._tenant_stats.setdefault(
            tenant, {"submitted": 0, "completed": 0, "rejected": 0,
                     "timeouts": 0})

    def _class_tally(self, slo_class: str) -> Dict[str, int]:
        """Caller holds the lock."""
        return self._class_stats.setdefault(
            slo_class, {"submitted": 0, "completed": 0, "rejected": 0,
                        "timeouts": 0, "in_slo": 0})

    def _capacity(self) -> Optional[float]:
        """Measured solved-RHS/s estimate: the dispatch EWMA once any
        batch has run, else the pessimistic phase-profile seed taken
        at registration (max_batch lanes / (measured step x maxiter)),
        else None - the auto shed rungs stay off until the service has
        MEASURED something."""
        return self._rate_ewma if self._rate_ewma is not None \
            else self._rate_seed

    def _drain_eta(self) -> float:
        """retry_after_s hint for a shed rejection: the measured time
        to drain the current backlog (depth / capacity), floored at
        one max_wait so the hint is never zero."""
        with self._lock:
            depth = self._queue.depth()
        cap = self._capacity()
        floor = max(self.config.max_wait_s, 1e-3)
        if cap is None or cap <= 0:
            return 4 * floor
        return max(depth / cap, floor)

    def _evaluate_shed(self, now: float) -> int:
        """Re-derive the ladder level from the current queue depth;
        emits the ``shed`` transition event + gauge on change and
        resets the defer-note throttle.  Returns the level."""
        with self._lock:
            depth = self._queue.depth()
            changed = self._shed.evaluate(depth, self._capacity())
            level = self._shed.level
            name = self._shed.name
            if changed:
                self._defer_noted.clear()
        if changed:
            from ..telemetry import events
            from ..telemetry.registry import REGISTRY

            REGISTRY.gauge(
                "serve_shed_level",
                "shed-ladder level (0 ok, 1 degrade, 2 defer, "
                "3 reject)").set(level)
            events.emit("shed", level=level, queue_depth=depth,
                        name=name,
                        capacity_rhs_per_s=self._capacity())
        return level

    def _defer_classes(self) -> frozenset:
        """SLO classes the ladder's defer rung names (level >= 2)."""
        if self._shed.level < 2:
            return frozenset()
        return frozenset(name for name, cls in self._classes.items()
                         if cls.defer_ok)

    def _active_defer(self) -> frozenset:
        """The defer set that actually applies right now.  Deferral is
        a RELATIVE priority - bulk yields capacity to gold/silver -
        never an absolute hold: when nothing non-deferred is queued or
        in flight, holding the backlog would serve nobody and (with no
        deadlines to expire) wedge it forever, since the ladder can
        only descend when depth falls and depth can only fall by
        dispatching.  Caller need not hold the lock (the RLock makes
        the depth reads safe either way)."""
        defer = self._defer_classes()
        if not defer:
            return defer
        with self._lock:
            if self._inflight:
                return defer
            depths = self._queue.depth_by_class()
            if any(n for cls, n in depths.items() if cls not in defer):
                return defer
        return frozenset()

    def _note_defers(self, now: float) -> None:
        """Emit one ``sched_dispatch`` decision="defer" event per held
        flow per ladder episode (throttled via ``_defer_noted``)."""
        defer = self._active_defer()
        if not defer:
            return
        with self._lock:
            held = [k for k in self._queue.deferred_ready(now, defer)
                    if k not in self._defer_noted]
            self._defer_noted.update(held)
            self._deferred += len(held)
        if not held:
            return
        from ..telemetry import events
        from ..telemetry.registry import REGISTRY

        for key in held:
            REGISTRY.counter(
                "serve_deferred_total",
                "dispatch-ready queues held by the shed ladder's "
                "defer rung", labelnames=("slo_class",)).inc(
                    slo_class=key[2])
            events.emit("sched_dispatch", tenant=key[1],
                        slo_class=key[2], decision="defer",
                        handle=key[0], shed_level=self._shed.level)

    def _note_tokens(self, tenant: str, tokens: float) -> None:
        from ..telemetry.registry import REGISTRY

        if tokens == float("inf"):
            return
        REGISTRY.gauge(
            "serve_tenant_tokens",
            "admission token-bucket balance per tenant",
            labelnames=("tenant",)).set(float(tokens), tenant=tenant)

    def _admission_reject(self, rid: str, handle: OperatorHandle,
                          tenant: str, slo_class: str, *,
                          retry_after_s: Optional[float],
                          reason: Optional[str],
                          tokens: Optional[float],
                          trace=None) -> Future:
        """Typed ADMISSION_REJECTED result - resolved immediately,
        never queued, never an exception (the polite refusal BEFORE
        the hard QueueFull bound)."""
        from ..telemetry import events
        from ..telemetry.registry import REGISTRY

        with self._lock:
            self._admission_rejected += 1
            self._tenant_tally(tenant)["rejected"] += 1
            self._class_tally(slo_class)["rejected"] += 1
        now = self._clock()
        if trace is not None:
            trace.span("admission", start_s=now, duration_s=0.0,
                       decision="rejected", reason=reason)
            trace.span("result", start_s=now, duration_s=0.0,
                       status="ADMISSION_REJECTED")
        if self._slo is not None:
            # a turned-away request is a broken promise from the
            # caller's seat: it burns error budget
            self._slo.observe(tenant, slo_class, now, False)
        REGISTRY.counter(
            "serve_admission_rejected_total",
            "requests refused by admission control (token bucket or "
            "shed ladder)", labelnames=("tenant", "reason")).inc(
                tenant=tenant, reason=reason or "unknown")
        events.emit("admission", request_id=rid, tenant=tenant,
                    slo_class=slo_class, decision="rejected",
                    reason=reason,
                    retry_after_s=(round(float(retry_after_s), 6)
                                   if retry_after_s is not None
                                   else None),
                    tokens=(round(float(tokens), 6)
                            if tokens is not None else None),
                    handle=handle.key)
        fut: Future = Future()
        fut.set_result(RequestResult(
            request_id=rid, status="ADMISSION_REJECTED",
            converged=False, timed_out=False, x=None, iterations=0,
            residual_norm=float("nan"), wait_s=0.0, solve_s=0.0,
            latency_s=0.0, bucket=0, occupancy=0.0, solve_id=None,
            attempts=0, tenant=tenant, slo_class=slo_class,
            retry_after_s=retry_after_s))
        return fut

    # -- circuit breaker -------------------------------------------------

    def _breaker_refuses(self, key: str, now: float,
                         rid: str) -> bool:
        """True when the handle's breaker refuses this submit.  An
        open breaker past its cooldown transitions to half_open and
        admits exactly ONE probe (recorded by request id so a probe
        that never dispatches can release the slot); further submits
        while the probe is in flight are refused."""
        if self.config.breaker_threshold <= 0:
            return False
        with self._lock:
            br = self._breakers.get(key)
            if br is None or br.state == "closed":
                return False
            if br.state == "open":
                if now < br.opened_t + self.config.breaker_cooldown_s:
                    return True
                br.state = "half_open"
                br.probing = False
                br.probe_id = None
                self._note_breaker(key, br)
            # half_open: one probe at a time
            if br.probing:
                return True
            br.probing = True
            br.probe_id = rid
            return False

    def _breaker_release_probe(self, key: str, rid: str) -> None:
        """The half-open probe request left WITHOUT a dispatch
        (deadline expiry in queue, or its push failed): free the
        probe slot so the next submit can probe - the breaker stays
        half_open, no outcome was observed."""
        if self.config.breaker_threshold <= 0:
            return
        with self._lock:
            br = self._breakers.get(key)
            if br is not None and br.probing and br.probe_id == rid:
                br.probing = False
                br.probe_id = None

    def _breaker_note_outcome(self, key: str, ok: bool,
                              now: float) -> None:
        """Record a dispatch outcome for the handle's breaker: a
        failed batch (every live lane ERROR/BREAKDOWN) counts toward
        the consecutive-failure threshold; any success closes."""
        if self.config.breaker_threshold <= 0:
            return
        with self._lock:
            br = self._breakers.setdefault(key, _Breaker())
            if ok:
                changed = br.state != "closed" \
                    or br.consecutive_failures
                br.state = "closed"
                br.consecutive_failures = 0
                br.probing = False
                br.probe_id = None
                if changed:
                    self._note_breaker(key, br)
                return
            br.consecutive_failures += 1
            if br.state == "half_open" \
                    or br.consecutive_failures \
                    >= self.config.breaker_threshold:
                br.state = "open"
                br.opened_t = now
                br.probing = False
                br.probe_id = None
                self._note_breaker(key, br)

    def _note_breaker(self, key: str, br: _Breaker) -> None:
        """Emit the transition (caller holds the lock; host-side
        only)."""
        from ..telemetry import events
        from ..telemetry.registry import REGISTRY

        REGISTRY.gauge(
            "serve_breaker_state",
            "per-handle circuit-breaker state (0 closed, 1 half-open, "
            "2 open)", labelnames=("handle",)).set(
                {"closed": 0, "half_open": 1, "open": 2}[br.state],
                handle=key)
        events.emit("breaker_transition", handle=key, state=br.state,
                    consecutive_failures=br.consecutive_failures)

    def breaker_state(self, handle: OperatorHandle) -> str:
        with self._lock:
            br = self._breakers.get(handle.key)
            return br.state if br is not None else "closed"

    def _refuse(self, rid: str, handle: OperatorHandle, now: float,
                tenant: str = "default",
                slo_class: str = "silver", trace=None) -> Future:
        """Typed REFUSED result for an open breaker - resolved
        immediately, never queued."""
        from ..telemetry import events
        from ..telemetry.registry import REGISTRY

        with self._lock:
            self._refused += 1
        if trace is not None:
            trace.span("admission", start_s=now, duration_s=0.0,
                       decision="refused", reason="breaker_open")
            trace.span("result", start_s=now, duration_s=0.0,
                       status="REFUSED")
        if self._slo is not None:
            self._slo.observe(tenant, slo_class, now, False)
        REGISTRY.counter(
            "serve_refused_total",
            "requests refused by an open per-handle circuit breaker",
            labelnames=("handle",)).inc(handle=handle.key)
        events.emit("request_done", request_id=rid, status="REFUSED",
                    wait_s=0.0, handle=handle.key, tenant=tenant,
                    slo_class=slo_class)
        fut: Future = Future()
        fut.set_result(RequestResult(
            request_id=rid, status="REFUSED", converged=False,
            timed_out=False, x=None, iterations=0,
            residual_norm=float("nan"), wait_s=0.0, solve_s=0.0,
            latency_s=0.0, bucket=0, occupancy=0.0, solve_id=None,
            attempts=0, tenant=tenant, slo_class=slo_class))
        return fut

    def _requeue(self, req: QueuedRequest, status: str,
                 now: float) -> bool:
        """Re-enqueue a failed request under the retry policy; returns
        False (caller resolves the typed failure instead) when the
        queue is full."""
        from ..telemetry import events
        from ..telemetry.registry import REGISTRY

        retry = self.config.retry
        prev = (req.attempts, req.ready_t, req.enqueue_t)
        req.attempts += 1
        req.ready_t = now + retry.backoff_for(req.attempts)
        req.enqueue_t = now
        try:
            with self._cond:
                self._queue.push(req)
                self._retries += 1
                self._cond.notify_all()
        except QueueFull:
            # the retry is abandoned: undo the bookkeeping so the
            # resolved result reports the dispatches that actually
            # completed, not a phantom one
            req.attempts, req.ready_t, req.enqueue_t = prev
            return False
        REGISTRY.counter(
            "serve_retries_total",
            "failed requests re-enqueued by the retry policy",
            labelnames=("handle", "status")).inc(
                handle=req.handle_key, status=status)
        events.emit("request_retry", request_id=req.request_id,
                    attempt=req.attempts, status=status,
                    handle=req.handle_key,
                    ready_in_s=round(float(req.ready_t - now), 6))
        if req.trace is not None:
            # child of the failed attempt's solve span (the current
            # head); the next attempt's queue_wait chains off it
            req.trace.span("retry", start_s=now,
                           duration_s=float(req.ready_t - now),
                           attempt=req.attempts, status=status)
        return True

    # -- dispatch --------------------------------------------------------

    def pump(self, now: Optional[float] = None) -> int:
        """Advance the policy once at ``now`` (manual-clock mode; the
        worker threads call the same step on real time).  Returns the
        number of batches dispatched."""
        return self._step(self._clock() if now is None else now)

    def _step(self, now: float, drain: bool = False) -> int:
        if self._n_workers > 1 and not self._manual:
            # multi-worker pool: concurrent passes are the point;
            # quiescence rides the in-flight counter, not this lock
            return self._step_locked(now, drain)
        with self._dispatch_lock:
            return self._step_locked(now, drain)

    def _step_locked(self, now: float, drain: bool = False) -> int:
        """One policy pass: sweep expired deadlines, note shed-held
        flows, then dispatch scheduler-chosen batches one at a time
        until nothing is dispatchable at ``now``.  In single-worker /
        manual mode the caller holds ``_dispatch_lock``; in a
        multi-worker pool several passes run concurrently, each pop
        atomically claiming one batch (``_inflight`` counts the
        claims, which is what drain() proves quiescence with)."""
        with self._lock:
            timeouts = self._queue.take_expired(now)
            depth = self._queue.depth()
        from ..telemetry.registry import REGISTRY

        REGISTRY.gauge("serve_queue_depth",
                       "requests pending in the solver service "
                       "queues").set(depth)
        for req in timeouts:
            self._finish_timeout(req, now)
        self._evaluate_shed(now)
        self._note_defers(now)
        dispatched = 0
        while True:
            defer = self._active_defer()
            with self._cond:
                batch = self._queue.pop_next(now, drain=drain,
                                             defer=defer)
                if batch is not None:
                    self._inflight += 1
            if batch is None:
                break
            try:
                self._run_batch(batch)
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()
            dispatched += 1
            # real-clock passes advance time batch-by-batch: a request
            # that arrived (or aged past max_wait) while the previous
            # batch solved competes in THIS pass - the weighted-fair
            # pick must see it, or a long backlog pass would starve
            # newcomers exactly the way DRR exists to prevent.  Manual
            # mode keeps the frozen `now` (fake-clock determinism)
            if not self._manual:
                now = self._clock()
            # dispatching drained the queue: the ladder may step DOWN
            # mid-pass, releasing deferred flows for this same pass
            self._evaluate_shed(now)
        return dispatched

    def _finish_timeout(self, req: QueuedRequest, now: float) -> None:
        from ..telemetry import events
        from ..telemetry.registry import REGISTRY

        # an expired half-open PROBE never dispatched: release the
        # slot so the handle is not wedged refusing forever
        self._breaker_release_probe(req.handle_key, req.request_id)
        wait = now - req.enqueue_t
        result = RequestResult(
            request_id=req.request_id, status="TIMEOUT",
            converged=False, timed_out=True, x=None, iterations=0,
            residual_norm=float("nan"), wait_s=float(wait), solve_s=0.0,
            latency_s=float(wait), bucket=0, occupancy=0.0,
            solve_id=None, attempts=req.attempts,
            degraded=req.degraded, tenant=req.tenant,
            slo_class=req.slo_class)
        with self._lock:
            self._timeouts += 1
            self._tenant_tally(req.tenant)["timeouts"] += 1
            self._class_tally(req.slo_class)["timeouts"] += 1
            # a deadline expiry is pure queue wait - it belongs in the
            # wait distribution (there is no solve wall to record)
            self._waits.append(float(wait))
        REGISTRY.counter("serve_timeouts_total",
                         "requests that expired their deadline in "
                         "queue (typed TIMEOUT results)",
                         labelnames=("handle",)).inc(
                             handle=req.handle_key)
        events.emit("request_done", request_id=req.request_id,
                    status="TIMEOUT", wait_s=float(wait),
                    handle=req.handle_key, tenant=req.tenant,
                    slo_class=req.slo_class)
        if req.trace is not None:
            req.trace.span("queue_wait", start_s=req.enqueue_t,
                           duration_s=float(wait),
                           attempt=req.attempts + 1)
            req.trace.span("result", start_s=now, duration_s=0.0,
                           status="TIMEOUT")
        if self._slo is not None:
            self._slo.observe(req.tenant, req.slo_class, now, False)
        if not req.future.done():
            req.future.set_result(result)

    def _engine(self, handle: OperatorHandle, b_stack: np.ndarray,
                tols: np.ndarray, deflate=None, basis=None,
                flight=None):
        """One batched solve of the handle's operator (the compiled
        hot path every dispatch and warmup shares).  Mesh handles ride
        the handle's prepared dispatcher - no per-batch plan/partition
        host work.  ``deflate``/``basis``/``flight`` are the recycling
        lanes (:class:`RecyclePolicy`); warmup passes none of them."""
        if handle.distributed:
            return handle.dispatcher.solve(b_stack, tol=tols,
                                           deflate=deflate,
                                           basis=basis, flight=flight)
        from ..solver.many import solve_many

        return solve_many(handle.a, b_stack, tol=tols,
                          maxiter=handle.maxiter, m=handle.precond_obj,
                          method=handle.method,
                          check_every=handle.check_every,
                          fault=handle.inject, deflate=deflate,
                          basis=basis, flight=flight)

    # -- Krylov recycling (ServiceConfig.recycle) ------------------------

    def _on_solver_evicted(self, key) -> None:
        """dist_cg LRU eviction: a handle whose compiled solvers were
        dropped loses its RecycleSpace too (the space rides the cache;
        a later dispatch re-traces AND re-harvests, loudly)."""
        with self._lock:
            handles = list(self._handles.values())
        for h in handles:
            disp = h.dispatcher
            if disp is None or h.recycle_space is None:
                continue
            kb = disp._key_base
            if isinstance(key, tuple) and len(key) >= len(kb) \
                    and tuple(key[: len(kb)]) == kb:
                self._drop_recycle_space(h)

    def _drop_recycle_space(self, handle: OperatorHandle) -> None:
        """Drop a handle's RecycleSpace and reset its schedule (shared
        by the LRU-eviction listener and the defensive
        BREAKDOWN-under-deflation path) - a later dispatch re-harvests
        from scratch, loudly counted."""
        from ..telemetry.registry import REGISTRY

        with self._lock:
            handle.recycle_space = None
            handle.recycle_info = None
            handle.recycle_frozen = False
            handle.recycle_stale = 0
            handle.recycle_deflated_since_harvest = 0
            self._recycle_dropped += 1
        REGISTRY.counter(
            "serve_recycle_spaces_dropped_total",
            "per-handle RecycleSpaces dropped (LRU eviction of the "
            "handle's compiled solvers, or a defensive drop after "
            "BREAKDOWN under deflation)").inc()

    def _recycle_lane(self, handle: OperatorHandle):
        """``(deflate, basis, flight)`` for the next live dispatch of
        this handle under the quality schedule (see RecyclePolicy)."""
        policy = self.config.recycle
        if policy is None or handle.method != "batched":
            return None, None, None
        harvesting = not handle.recycle_frozen
        if handle.recycle_frozen and policy.refresh_every > 0 \
                and handle.recycle_deflated_since_harvest \
                >= policy.refresh_every:
            harvesting = True          # scheduled drift refresh
        if not harvesting:
            return handle.recycle_space, None, None
        from ..solver.recycle import BasisConfig
        from ..telemetry.flight import FlightConfig

        cap = policy.capacity
        basis = (BasisConfig(capacity=cap) if cap is not None
                 else BasisConfig.for_solve(handle.maxiter))
        flight = FlightConfig.for_solve(handle.maxiter, stride=1)
        return handle.recycle_space, basis, flight

    def _recycle_after(self, handle: OperatorHandle, res, n_live: int,
                      deflate, basis) -> None:
        """Post-dispatch half of the schedule: harvest/accumulate,
        track the improvement, emit the events/gauges."""
        from ..solver import recycle as rec

        policy = self.config.recycle
        iters = np.asarray(res.iterations)[:n_live]
        statuses = np.asarray(res.status)
        mean_iters = float(iters.mean()) if iters.size else 0.0
        with self._lock:
            if self._recycle_first_iters is None and basis is not None:
                self._recycle_first_iters = mean_iters
            self._recycle_last_iters = mean_iters
        if deflate is not None:
            with self._lock:
                self._recycle_applied += 1
                handle.recycle_deflated_since_harvest += 1
            rec.note_applied(deflate.k, int(round(mean_iters)),
                             handle.recycle_baseline_iters,
                             handle=handle.key)
            from ..solver.status import CGStatus as _St

            if any(int(sv) == int(_St.BREAKDOWN)
                   for sv in statuses[:n_live]):
                # defensive: a deflated lane must never be the thing
                # that breaks a solve - drop the space, loudly
                self._drop_recycle_space(handle)
                return
        if basis is None:
            return
        if handle.recycle_baseline_iters is None:
            handle.recycle_baseline_iters = mean_iters
        try:
            space, info = rec.harvest_space(
                handle.a, res, k=policy.k,
                prev=handle.recycle_space,
                n_rhs=int(np.asarray(res.x).shape[1]), note=False)
        except rec.HarvestError:
            from ..telemetry.registry import REGISTRY

            REGISTRY.counter(
                "serve_recycle_harvest_failures_total",
                "harvests the recycling schedule attempted that the "
                "record could not support").inc()
            with self._lock:
                handle.recycle_stale += 1
                # a FAILED refresh round still closes the round: the
                # counter resets so the next refresh waits another
                # refresh_every dispatches instead of re-paying the
                # recorders + harvest on every batch forever
                handle.recycle_deflated_since_harvest = 0
                if handle.recycle_stale >= policy.patience:
                    handle.recycle_frozen = True
            return
        rec.note_harvest(info, handle=handle.key)
        with self._lock:
            handle.recycle_space = space
            handle.recycle_info = info
            handle.recycle_harvests += 1
            handle.recycle_deflated_since_harvest = 0
            self._recycle_harvests += 1
            best = handle.recycle_best_iters
            if best is None \
                    or mean_iters <= best - policy.min_improvement:
                handle.recycle_best_iters = mean_iters \
                    if best is None else min(best, mean_iters)
                handle.recycle_stale = 0
                handle.recycle_frozen = False
            else:
                handle.recycle_stale += 1
                if handle.recycle_stale >= policy.patience:
                    # quality plateau: drop the recorders, keep the
                    # space - pure deflated dispatches from here
                    handle.recycle_frozen = True

    def _run_batch(self, batch: Batch) -> None:
        from ..solver.many import stack_columns
        from ..telemetry import events
        from ..telemetry.registry import REGISTRY

        # wait_s baseline is taken HERE, not at pop time: several
        # batches popped by one step run sequentially, and batch N's
        # queue wait honestly includes batches 1..N-1's solve walls
        # (head-of-line blocking is real latency; under a fake clock
        # the two timestamps coincide and tests stay deterministic)
        now = self._clock()
        reqs = batch.requests
        handle: OperatorHandle = reqs[0].handle
        m, k = len(reqs), batch.bucket
        if self._queue.sched is not None:
            # the weighted-fair pick, priced: what the starvation-
            # bound analysis audits after the fact
            events.emit("sched_dispatch", tenant=batch.tenant,
                        slo_class=batch.slo_class,
                        decision="dispatch", handle=handle.key,
                        cost=round(self._cost_model.price(handle), 9),
                        reason=batch.reason, n_requests=m)
        for r in reqs:
            if r.trace is not None:
                # the attempt's queue residency ends HERE; sched is
                # the dispatch decision that ended it
                r.trace.span("queue_wait", start_s=r.enqueue_t,
                             duration_s=float(now - r.enqueue_t),
                             attempt=r.attempts + 1)
                r.trace.span("sched", start_s=now, duration_s=0.0,
                             decision="dispatch", reason=batch.reason,
                             bucket=k)
        b_stack = stack_columns([r.b for r in reqs], k,
                                dtype=np.dtype(handle.dtype_name))
        tols = np.full((k,), reqs[0].tol,
                       dtype=np.dtype(handle.dtype_name))
        tols[:m] = [r.tol for r in reqs]
        r_deflate, r_basis, r_flight = self._recycle_lane(handle)
        # wire-byte attribution rides dist_cg's LAST-built cost note,
        # which is a process-global: only a serialized dispatcher
        # (manual pumps or the single worker) can attribute it to THIS
        # batch.  A concurrent pool meters device-seconds/iterations
        # and reports wire as 0 rather than guessing
        meter_wire = (self._usage is not None and handle.distributed
                      and (self._manual or self._n_workers == 1))
        if meter_wire:
            from ..parallel import dist_cg

            dist_cg.reset_last_comm_cost()
        t0 = time.perf_counter()
        with events.solve_scope() as solve_id:
            events.emit("batch_dispatch", handle=handle.key, bucket=k,
                        n_requests=m, reason=batch.reason,
                        occupancy=round(batch.occupancy, 6),
                        **({"deflate_k": r_deflate.k}
                           if r_deflate is not None else {}))
            try:
                # recycle kwargs only when the lane is live: the plain
                # dispatch keeps the pre-recycling 3-arg call (test
                # harnesses wrap _engine with that signature)
                recycle_kw = {}
                if r_deflate is not None or r_basis is not None:
                    recycle_kw = dict(deflate=r_deflate, basis=r_basis,
                                      flight=r_flight)
                res = self._engine(handle, b_stack, tols, **recycle_kw)
                x = np.asarray(res.x)          # sync: the solve is done
                iters = np.asarray(res.iterations)
                rnorm = np.asarray(res.residual_norm)
                conv = np.asarray(res.converged)
                stat = np.asarray(res.status)
                if self.config.recycle is not None:
                    self._recycle_after(handle, res, m, r_deflate,
                                        r_basis)
            except Exception as exc:
                # the typed-terminal-result contract holds for engine
                # failures too: every lane of the batch resolves to a
                # status="ERROR" RequestResult (a raised future would
                # blow up any caller looping fut.result() - the CLI
                # replay included) and the worker survives
                solve_s = time.perf_counter() - t0
                with self._lock:
                    # the failed dispatch still WAS a dispatch: batch
                    # bookkeeping stays consistent with the
                    # batch_dispatch event already emitted (during an
                    # incident stats()/batch_log must not disagree
                    # with the event stream)
                    self._errors += m
                    self._n_batches += 1
                    self._lane_total += k
                    self._padded_lanes += k - m
                    self._occupancy_sum += batch.occupancy
                    self._bucket_counts[k] = \
                        self._bucket_counts.get(k, 0) + 1
                    self._batch_log.append({
                        "handle": handle.key, "bucket": k,
                        "n_requests": m, "reason": batch.reason,
                        "solve_s": float(solve_s),
                        "solve_id": solve_id,
                        "error": repr(exc)[-200:],
                        "request_ids": [r.request_id for r in reqs]})
                REGISTRY.counter("serve_batches_total",
                                 "microbatches dispatched",
                                 labelnames=("handle", "reason")).inc(
                                     handle=handle.key,
                                     reason=batch.reason)
                if self._usage is not None:
                    # the failed dispatch burned real device-seconds
                    # and somebody caused it: metered, iterations and
                    # wire unknown (0)
                    self._usage.note_batch(
                        solve_id=solve_id, handle=handle.key,
                        solve_s=float(solve_s),
                        mesh_size=(int(handle.mesh.devices.size)
                                   if handle.distributed else 1),
                        batch_iterations=0,
                        wire_bytes_per_iteration=0.0,
                        lanes=[{"request_id": r.request_id,
                                "tenant": r.tenant,
                                "slo_class": r.slo_class,
                                "iterations": 0,
                                "trace_id": (r.trace.trace_id
                                             if r.trace is not None
                                             else None)}
                               for r in reqs])
                retry_p = self.config.retry
                for r in reqs:
                    wait = float(now - r.enqueue_t)
                    if r.trace is not None:
                        r.trace.span("solve", start_s=now,
                                     duration_s=float(solve_s),
                                     solve_id=solve_id, bucket=k,
                                     status="ERROR",
                                     error=repr(exc)[-200:])
                    if retry_p is not None \
                            and "ERROR" in retry_p.statuses \
                            and r.attempts < retry_p.max_retries \
                            and not r.future.done() \
                            and self._requeue(r, "ERROR",
                                              self._clock()):
                        continue
                    if r.trace is not None:
                        r.trace.span("result",
                                     start_s=now + float(solve_s),
                                     duration_s=0.0, status="ERROR")
                    if self._slo is not None:
                        self._slo.observe(r.tenant, r.slo_class,
                                          self._clock(), False)
                    events.emit("request_done",
                                request_id=r.request_id, status="ERROR",
                                wait_s=wait, handle=handle.key,
                                error=repr(exc)[-200:],
                                tenant=r.tenant,
                                slo_class=r.slo_class)
                    REGISTRY.counter(
                        "serve_requests_done_total",
                        "requests finished by the solver service",
                        labelnames=("handle", "status")).inc(
                            handle=handle.key, status="ERROR")
                    if not r.future.done():
                        r.future.set_result(RequestResult(
                            request_id=r.request_id, status="ERROR",
                            converged=False, timed_out=False, x=None,
                            iterations=0,
                            residual_norm=float("nan"), wait_s=wait,
                            solve_s=float(solve_s),
                            latency_s=wait + float(solve_s), bucket=k,
                            occupancy=batch.occupancy,
                            solve_id=solve_id,
                            attempts=r.attempts + 1,
                            degraded=r.degraded, tenant=r.tenant,
                            slo_class=r.slo_class))
                self._breaker_note_outcome(handle.key, False,
                                           self._clock())
                return
            solve_s = time.perf_counter() - t0
            if self._usage is not None:
                mesh_size = (int(handle.mesh.devices.size)
                             if handle.distributed else 1)
                wire_per_iter = 0.0
                if meter_wire:
                    from ..parallel import dist_cg

                    last = dist_cg.last_comm_cost()
                    if last is not None:
                        # per-device interconnect bytes x mesh size =
                        # total wire volume per iteration
                        wire_per_iter = float(
                            last[0].per_iteration.wire_bytes
                        ) * mesh_size
                self._usage.note_batch(
                    solve_id=solve_id, handle=handle.key,
                    solve_s=float(solve_s), mesh_size=mesh_size,
                    batch_iterations=max(
                        int(iters[j]) for j in range(m)),
                    wire_bytes_per_iteration=wire_per_iter,
                    lanes=[{"request_id": r.request_id,
                            "tenant": r.tenant,
                            "slo_class": r.slo_class,
                            "iterations": int(iters[j]),
                            "trace_id": (r.trace.trace_id
                                         if r.trace is not None
                                         else None)}
                           for j, r in enumerate(reqs)])
            results = []
            retry_p = self.config.retry
            lane_statuses = []
            for j, r in enumerate(reqs):
                status = CGStatus(int(stat[j])).name
                lane_statuses.append(status)
                wait = float(now - r.enqueue_t)
                latency = wait + solve_s
                if r.trace is not None:
                    r.trace.span("solve", start_s=now,
                                 duration_s=float(solve_s),
                                 solve_id=solve_id, bucket=k,
                                 occupancy=round(batch.occupancy, 6),
                                 iterations=int(iters[j]),
                                 status=status)
                if status == "BREAKDOWN":
                    # the problem's fault, typed and loud: the shared
                    # solve_fault event + counter, from the lane that
                    # actually broke
                    from ..telemetry.session import note_breakdown

                    site = (handle.inject.site
                            if handle.inject is not None else "unknown")
                    note_breakdown(site, int(iters[j]),
                                   request_id=r.request_id,
                                   handle=handle.key)
                if retry_p is not None and status in retry_p.statuses \
                        and r.attempts < retry_p.max_retries \
                        and not r.future.done() \
                        and self._requeue(r, status, self._clock()):
                    # re-enqueued, not re-solved inline: the lane goes
                    # back through the microbatch queue with backoff
                    continue
                result = RequestResult(
                    request_id=r.request_id, status=status,
                    converged=bool(conv[j]), timed_out=False,
                    # a copy, not a view: x[:, j] would pin the whole
                    # (n, k) batch solution for the result's lifetime
                    x=np.ascontiguousarray(x[:, j]),
                    iterations=int(iters[j]),
                    residual_norm=float(rnorm[j]), wait_s=wait,
                    solve_s=float(solve_s), latency_s=float(latency),
                    bucket=k, occupancy=batch.occupancy,
                    solve_id=solve_id, attempts=r.attempts + 1,
                    degraded=r.degraded, tenant=r.tenant,
                    slo_class=r.slo_class)
                results.append((r, result))
                events.emit("request_done", request_id=r.request_id,
                            status=status, wait_s=wait,
                            solve_s=float(solve_s),
                            latency_s=float(latency),
                            iterations=int(iters[j]),
                            converged=bool(conv[j]), handle=handle.key,
                            tenant=r.tenant, slo_class=r.slo_class)
                if r.trace is not None:
                    r.trace.span("result",
                                 start_s=now + float(solve_s),
                                 duration_s=0.0, status=status,
                                 converged=bool(conv[j]))
                REGISTRY.counter(
                    "serve_requests_done_total",
                    "requests finished by the solver service",
                    labelnames=("handle", "status")).inc(
                        handle=handle.key, status=status)
                REGISTRY.histogram(
                    "serve_request_latency_seconds",
                    "submit-to-result latency (queue wait + batched "
                    "solve wall)", labelnames=("handle",),
                    buckets=LATENCY_BUCKETS).observe(
                        latency, handle=handle.key)
        REGISTRY.counter("serve_batches_total",
                         "microbatches dispatched",
                         labelnames=("handle", "reason")).inc(
                             handle=handle.key, reason=batch.reason)
        REGISTRY.gauge("serve_batch_occupancy",
                       "requests/bucket of the most recent dispatched "
                       "batch", labelnames=("handle",)).set(
                           batch.occupancy, handle=handle.key)
        REGISTRY.gauge("serve_batch_padding_fraction",
                       "padded (wasted) lane fraction of the most "
                       "recent dispatched batch",
                       labelnames=("handle",)).set(
                           batch.padding_fraction, handle=handle.key)
        REGISTRY.counter("serve_lanes_total",
                         "solver lanes dispatched (incl. padding)",
                         labelnames=("handle",)).inc(k,
                                                     handle=handle.key)
        if k > m:
            REGISTRY.counter("serve_padded_lanes_total",
                             "zero-RHS pad lanes dispatched "
                             "(bucket - occupancy waste)",
                             labelnames=("handle",)).inc(
                                 k - m, handle=handle.key)
        with self._lock:
            self._n_batches += 1
            self._lane_total += k
            self._padded_lanes += k - m
            self._occupancy_sum += batch.occupancy
            self._bucket_counts[k] = self._bucket_counts.get(k, 0) + 1
            # the measured capacity estimate the shed ladder prices
            # against, and the scheduler's cost-model feedback.  The
            # per-batch sample (lanes / its own solve wall) is scaled
            # by the worker count: batches overlap across the pool, so
            # the service drains ~N batches per batch-wall.  Exact
            # under the saturation the ladder cares about (an idle
            # pool overestimates, which only RAISES auto thresholds -
            # shedding never fires early on the scaling)
            self._cost_model.observe(handle, float(solve_s))
            if solve_s > 0:
                rate = self._n_workers * m / float(solve_s)
                self._rate_ewma = rate if self._rate_ewma is None \
                    else 0.7 * self._rate_ewma + 0.3 * rate
            slo_obs = []
            for _, result in results:
                self._completed += 1
                if result.converged:
                    self._converged += 1
                self._latencies.append(result.latency_s)
                self._waits.append(result.wait_s)
                self._solves.append(result.solve_s)
                self._tenant_tally(result.tenant)["completed"] += 1
                ctally = self._class_tally(result.slo_class)
                ctally["completed"] += 1
                cls = self._classes.get(result.slo_class)
                target = cls.target_latency_s if cls is not None \
                    else None
                in_slo = result.converged and (
                    target is None or result.latency_s <= target)
                if in_slo:
                    ctally["in_slo"] += 1
                slo_obs.append((result.tenant, result.slo_class,
                                in_slo))
                self._class_latencies.setdefault(
                    result.slo_class,
                    deque(maxlen=self.config.keep_latency_samples)
                ).append(result.latency_s)
            self._batch_log.append({
                "handle": handle.key, "bucket": k, "n_requests": m,
                "reason": batch.reason, "solve_s": float(solve_s),
                "solve_id": solve_id,
                "request_ids": [r.request_id for r in reqs]})
        if self._slo is not None:
            # the SAME in-SLO verdict the class tally just recorded,
            # observed on the service clock (fake-clock drill rides
            # this determinism)
            t_done = self._clock()
            for tenant, slo_class, in_slo in slo_obs:
                self._slo.observe(tenant, slo_class, t_done, in_slo)
        # breaker: a dispatch where every live lane failed with an
        # ERROR/BREAKDOWN counts toward the consecutive-failure
        # threshold; anything else closes the breaker
        failed = bool(lane_statuses) and all(
            s in ("ERROR", "BREAKDOWN") for s in lane_statuses)
        self._breaker_note_outcome(handle.key, not failed,
                                   self._clock())
        for r, result in results:
            if not r.future.done():
                r.future.set_result(result)

    # -- lifecycle -------------------------------------------------------

    def drain(self) -> None:
        """Flush every pending request NOW (partial batches dispatch
        immediately with reason="drain", deferred classes included);
        returns when the queues are empty AND no batch is in flight.
        The service stays open.

        Quiescence proof: every dispatch - manual, worker, or drain -
        increments ``_inflight`` atomically with its pop and
        decrements it (with a notify) when the batch resolves, so
        ``depth == 0 and _inflight == 0`` under the lock means every
        submitted request has resolved - a caller timing a replay
        window after drain() includes the last batch's solve wall."""
        while True:
            self._step(self._clock(), drain=True)
            with self._cond:
                if self._queue.depth() == 0 and self._inflight == 0:
                    return
                if self._inflight:
                    # another worker owns the last batches: wait for
                    # their notify instead of spinning on pop_next
                    self._cond.wait(timeout=0.05)

    def close(self) -> None:
        """Stop accepting work, drain what is queued, stop the worker
        pool.  Idempotent; submits after close raise
        :class:`ServiceClosed`."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self.drain()
        if self._evict_listener is not None:
            from ..parallel import dist_cg

            dist_cg.remove_evict_listener(self._evict_listener)
            self._evict_listener = None
        if self._workers:
            with self._cond:
                self._stop = True
                self._cond.notify_all()
            for t in self._workers:
                t.join(timeout=5.0)
            self._workers = []
        # the data plane stops FIRST (no new submissions can arrive
        # once the service refuses them), the ops plane outlives the
        # drain (a scrape during shutdown sees status "closed", not a
        # connection refusal), then stops
        net, self._net_server = self._net_server, None
        if net is not None:
            net.stop()
        ops, self._ops_server = self._ops_server, None
        if ops is not None:
            ops.stop()

    def __enter__(self) -> "SolverService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _worker_loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                now = self._clock()
                # re-derive the ladder level from the current depth
                # before sleeping: a pass that just drained the queue
                # may have dropped the level, releasing deferred flows
                # whose max_wait must now drive the wake (the RLock
                # makes the re-entrant evaluate safe; a transition
                # still emits its shed event)
                self._evaluate_shed(now)
                wake = self._queue.next_wake(
                    now, defer=self._active_defer())
                if wake is None:
                    self._cond.wait()
                elif wake > now:
                    self._cond.wait(timeout=wake - now)
            if self._stop:
                return
            self._step(self._clock())

    # -- reporting -------------------------------------------------------

    def queue_depth(self) -> int:
        with self._lock:
            return self._queue.depth()

    def batch_log(self) -> List[dict]:
        with self._lock:
            return list(self._batch_log)

    def usage_ledger(self):
        """The per-tenant :class:`serve.usage.UsageLedger` (``None``
        unless ``ServiceConfig(usage=True)``)."""
        return self._usage

    def slo_tracker(self):
        """The :class:`telemetry.slo.SLOTracker` (``None`` unless
        ``ServiceConfig(slo=...)``).  Its ``burn_rate()`` is the
        documented hook external policy (a future shed rung, an
        autoscaler) may poll."""
        return self._slo

    # -- the network ops plane (serve.ops) -------------------------------

    def serve_ops(self, port: int, *, host: Optional[str] = None,
                  token: Optional[str] = None):
        """Start the read-only HTTP ops plane on ``port`` (0 =
        ephemeral) and return the :class:`serve.ops.OpsServer`.

        One plane per service: a second call raises (two servers
        scraping one registry would double-count nothing but confuse
        everything).  ``ServiceConfig(ops_port=...)`` calls this at
        construction; :meth:`close` tears it down.
        """
        from .ops import OpsServer

        with self._lock:
            if self._ops_server is not None:
                raise RuntimeError(
                    "ops plane already running on port "
                    f"{self._ops_server.port}; one OpsServer per "
                    "service")
            server = OpsServer(
                self, port=int(port),
                host=host if host is not None else self.config.ops_host,
                token=token if token is not None
                else self.config.ops_token)
            self._ops_server = server
        server.start()
        return server

    def ops_server(self):
        """The running :class:`serve.ops.OpsServer` (``None`` when the
        plane is off)."""
        return self._ops_server

    # -- the network data plane (serve.net) -------------------------------

    def serve_net(self, port: int, *, host: Optional[str] = None,
                  keyring=None):
        """Start the authenticated HTTP data plane on ``port`` (0 =
        ephemeral) and return the :class:`serve.net.NetServer`.

        ``keyring`` (a :class:`serve.auth.TokenKeyring`) is mandatory:
        the whole point of the plane is that tenant tags are derived
        from credentials, so an unauthenticated data plane is a
        configuration error, not a default.  One plane per service;
        ``ServiceConfig(net_port=..., net_keyring=...)`` calls this at
        construction, :meth:`close` tears it down.
        """
        from .net import NetServer

        if keyring is None:
            keyring = self.config.net_keyring
        with self._lock:
            if self._net_server is not None:
                raise RuntimeError(
                    "data plane already running on port "
                    f"{self._net_server.port}; one NetServer per "
                    "service")
            server = NetServer(
                self, port=int(port),
                host=host if host is not None
                else self.config.net_host,
                keyring=keyring)
            self._net_server = server
        server.start()
        return server

    def net_server(self):
        """The running :class:`serve.net.NetServer` (``None`` when the
        data plane is off)."""
        return self._net_server

    def handles(self) -> Dict[str, OperatorHandle]:
        """Snapshot of the registered operators by handle key (the
        data plane's ``GET /v1/handles`` discoverability source)."""
        with self._lock:
            return dict(self._handles)

    def readiness(self) -> dict:
        """The routing-grade readiness verdict ``GET /readyz`` serves.

        READ-ONLY by contract: reads ``_closed``, the breaker states,
        the shed ladder's current level and the SLO tracker's burn
        windows under the service lock - it never re-evaluates the
        ladder (that mutates state and emits events; the dispatch path
        owns it).  Four gates, each with an ``ok`` verdict and enough
        detail for a router to explain its decision:

        * ``accepting`` - the service has not been closed;
        * ``breakers``  - no per-handle circuit breaker is open
          (half-open probes count as recovering, not failing);
        * ``shed``      - the shed ladder sits at level 0;
        * ``slo_burn``  - no (flow, window) burns over its threshold.

        ``ready`` is the conjunction; ``failing`` names every gate
        that voted no, so a 503 body is actionable without scraping
        anything else.
        """
        now = self._clock()
        with self._lock:
            closed = self._closed
            open_breakers = sorted(
                key for key, br in self._breakers.items()
                if br.state == "open")
            shed_level = self._shed.level
            shed_name = self._shed.name
        burning = self._slo.burning(now) if self._slo is not None \
            else []
        gates = {
            "accepting": {"ok": not closed},
            "breakers": {"ok": not open_breakers,
                         "open": open_breakers},
            "shed": {"ok": shed_level == 0, "level": shed_level,
                     "name": shed_name},
            "slo_burn": {"ok": not burning, "burning": burning},
        }
        failing = [name for name in ("accepting", "breakers", "shed",
                                     "slo_burn")
                   if not gates[name]["ok"]]
        status = "closed" if closed else (
            "degraded" if failing else "ready")
        return {"ready": not failing, "status": status,
                "gates": gates, "failing": failing, "t": now}

    def stats(self) -> dict:
        """JSON-ready service summary: request/batch counts, occupancy
        and padding means, bucket usage, and EXACT latency percentiles
        over the last ``keep_latency_samples`` completions (the
        registry histogram additionally exports interpolated
        p50/p95/p99 over the full history for scrapes).  ``latency``
        is end-to-end; ``wait`` and ``solve`` split the same window
        into queueing delay vs batched solve wall (wait additionally
        includes deadline-expired requests - their whole latency IS
        queue wait)."""
        with self._lock:
            lat = sorted(self._latencies)
            waits = sorted(self._waits)
            solves = sorted(self._solves)
            n_batches = self._n_batches
            out = {
                "submitted": self._submitted,
                "completed": self._completed,
                "converged": self._converged,
                "timeouts": self._timeouts,
                "errors": self._errors,
                "queue_depth": self._queue.depth(),
                "batches": n_batches,
                "lanes_dispatched": self._lane_total,
                "padded_lanes": self._padded_lanes,
                "padding_fraction": (
                    self._padded_lanes / self._lane_total
                    if self._lane_total else 0.0),
                "occupancy_mean": (
                    self._occupancy_sum / n_batches if n_batches
                    else 0.0),
                "bucket_counts": {str(k): v for k, v in
                                  sorted(self._bucket_counts.items())},
                "retries": self._retries,
                "refused": self._refused,
                "degraded": self._degraded,
                "migrations": self._migrations,
                "breakers": {key: br.state
                             for key, br in self._breakers.items()
                             if br.state != "closed"},
            }
            # multi-tenant / overload story: per-tenant disposition +
            # live depth, per-class SLO accounting, and the shed
            # ladder's state - only when any of it is non-trivial, so
            # a plain single-tenant stats() keeps its PR 10 shape
            tenant_depth = self._queue.depth_by_tenant()
            if self._tenant_stats and (
                    len(self._tenant_stats) > 1
                    or set(self._tenant_stats) != {"default"}
                    or self._admission_rejected):
                out["tenants"] = {
                    t: {**tally, "depth": tenant_depth.get(t, 0)}
                    for t, tally in sorted(self._tenant_stats.items())}
            if self._class_stats and (
                    len(self._class_stats) > 1
                    or set(self._class_stats) != {"silver"}):
                classes = {}
                for name, tally in sorted(self._class_stats.items()):
                    cls = self._classes.get(name)
                    lats = sorted(self._class_latencies.get(name, ()))
                    classes[name] = {
                        **tally,
                        "target_latency_s": (cls.target_latency_s
                                             if cls is not None
                                             else None),
                        "p50_s": _percentile(lats, 0.50),
                        "p99_s": _percentile(lats, 0.99),
                    }
                out["classes"] = classes
            if self._shed.transitions or self._admission_rejected \
                    or self._deferred:
                out["shed"] = {
                    "level": self._shed.level,
                    "name": self._shed.name,
                    "transitions": self._shed.transitions,
                    "deferred_flows": self._deferred,
                    "admission_rejected": self._admission_rejected,
                    "capacity_rhs_per_s": self._capacity(),
                }
            if self.config.recycle is not None:
                out["recycle"] = {
                    "harvests": self._recycle_harvests,
                    "applied": self._recycle_applied,
                    "dropped": self._recycle_dropped,
                    "first_solve_iterations": self._recycle_first_iters,
                    "last_solve_iterations": self._recycle_last_iters,
                    "spaces": {
                        h.key: {
                            "k": (h.recycle_space.k
                                  if h.recycle_space is not None
                                  else None),
                            "harvests": h.recycle_harvests,
                            "frozen": h.recycle_frozen,
                            "baseline_iterations":
                                h.recycle_baseline_iters,
                        }
                        for h in self._handles.values()
                        if h.recycle_harvests
                        or h.recycle_space is not None},
                }
        # request observatory (own locks - outside the service lock)
        if self._slo is not None:
            out["slo"] = self._slo.snapshot(self._clock())
        if self._usage is not None:
            out["usage"] = self._usage.snapshot()
        out["latency"] = {
            "count": len(lat),
            "mean_s": float(np.mean(lat)) if lat else None,
            "max_s": float(lat[-1]) if lat else None,
            "p50_s": _percentile(lat, 0.50),
            "p95_s": _percentile(lat, 0.95),
            "p99_s": _percentile(lat, 0.99),
        }
        for key, vals in (("wait", waits), ("solve", solves)):
            out[key] = {
                "count": len(vals),
                "mean_s": float(np.mean(vals)) if vals else None,
                "max_s": float(vals[-1]) if vals else None,
                "p50_s": _percentile(vals, 0.50),
                "p95_s": _percentile(vals, 0.95),
                "p99_s": _percentile(vals, 0.99),
            }
        return out
