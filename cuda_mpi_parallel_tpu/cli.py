"""Command-line driver.

The reference's ``main()`` takes no arguments: problem, tolerance, maxit and
device id are all hardcoded (``CUDACG.cu:87,244-245``, SURVEY SS5 "Config").
This CLI exposes them all - ``--problem/--n/--tol/--maxiter/--precond/
--mesh/--device/--dtype`` per the north star - and reports what the
reference never does (iterations, residual, timing, optional history).

Examples::

    python -m cuda_mpi_parallel_tpu.cli --problem oracle
    python -m cuda_mpi_parallel_tpu.cli --problem poisson2d --n 1024 \
        --dtype float32 --tol 1e-5 --history
    python -m cuda_mpi_parallel_tpu.cli --problem poisson3d --n 64 --mesh 4
    python -m cuda_mpi_parallel_tpu.cli --problem mm --file thermal2.mtx \
        --precond jacobi --json
    python -m cuda_mpi_parallel_tpu.cli lint cuda_mpi_parallel_tpu/
    python -m cuda_mpi_parallel_tpu.cli serve --problem poisson2d \
        --n 32 --requests 32 --rate 2000 --max-batch 8

The ``lint`` subcommand mounts the graftlint static-analysis suite
(``cuda_mpi_parallel_tpu.analysis``): Mosaic tiling, VMEM budgets,
collective safety, DMA pairing, host-sync - the pre-hardware gate for
new kernels.  The ``serve`` subcommand replays a workload through the
microbatching solver service (``cuda_mpi_parallel_tpu.serve``) and
prints its throughput/latency/occupancy report.
"""
from __future__ import annotations

import argparse
import sys

import numpy as np


def _jax_backend_is_tpu() -> bool:
    """True on a compiled-TPU backend (the only place pallas kernels run
    compiled; everywhere else "auto" engine choices avoid interpret
    mode)."""
    import jax

    return jax.default_backend() == "tpu"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cuda_mpi_parallel_tpu",
        description="TPU-native conjugate-gradient solver framework")
    p.add_argument("--problem", default="oracle",
                   choices=["oracle", "poisson2d", "poisson3d", "random-spd",
                            "random-sparse", "mm"],
                   help="problem family (oracle = the reference's hardcoded "
                        "3x3 system, CUDACG.cu:74-117)")
    p.add_argument("--n", type=int, default=64,
                   help="grid extent per axis (poisson*) or matrix size "
                        "(random-*)")
    p.add_argument("--file", default=None,
                   help="Matrix Market path (--problem mm)")
    p.add_argument("--tol", type=float, default=1e-7,
                   help="absolute ||r|| tolerance (reference default 1e-7, "
                        "CUDACG.cu:245)")
    p.add_argument("--rtol", type=float, default=0.0,
                   help="relative tolerance (0 = reference-style absolute "
                        "only)")
    p.add_argument("--maxiter", type=int, default=2000,
                   help="iteration cap (reference default 2000, "
                        "CUDACG.cu:244)")
    p.add_argument("--precond", default=None,
                   choices=[None, "jacobi", "chebyshev", "bjacobi", "mg"],
                   help="preconditioner (chebyshev = polynomial in A, "
                        "bjacobi = dense block diagonal, mg = geometric "
                        "multigrid V-cycle for --matrix-free stencils; all "
                        "absent from the reference, which has no "
                        "preconditioning)")
    p.add_argument("--precond-degree", type=int, default=4,
                   help="Chebyshev term count, costing degree-1 matvecs per "
                        "application (--precond chebyshev)")
    p.add_argument("--block-size", type=int, default=8,
                   help="block-Jacobi block size (--precond bjacobi)")
    p.add_argument("--mesh", type=int, default=1,
                   help="number of devices for row-partitioned execution "
                        "(1 = single device)")
    p.add_argument("--csr-comm", default="allgather",
                   choices=["allgather", "ring", "ring-shiftell"],
                   help="distributed general-CSR schedule: all-gather x "
                        "every matvec; ring (rotate x-blocks around the "
                        "mesh via ppermute: O(n/P) memory, overlapped "
                        "compute); or ring-shiftell (same ring with the "
                        "pallas shift-ELL slab kernel for each local "
                        "multiply)")
    p.add_argument("--exchange", default=None,
                   choices=["auto", "gather", "allgather", "ring"],
                   help="distributed general-CSR halo wire "
                        "(parallel.exchange): 'gather' ships only the "
                        "coupled x entries as packed per-neighbor "
                        "ppermute rounds (node-aware SpMV - strictly "
                        "fewer wire bytes whenever coupling is sparse; "
                        "padding to the max neighbor is reported in "
                        "the comm record); 'allgather' forces the "
                        "legacy full-x collective; 'ring' is "
                        "--csr-comm ring; 'auto' lets the partition "
                        "plan (or, unplanned, the coupled-volume rule) "
                        "decide, falling back to allgather when "
                        "coupling approaches O(n).  Default: the "
                        "legacy --csr-comm lane, except that a --plan "
                        "auto plan scored for the gather wire runs it")
    p.add_argument("--device", default=None,
                   choices=[None, "tpu", "cpu"],
                   help="force a JAX platform (default: auto)")
    p.add_argument("--dtype", default="auto",
                   choices=["auto", "float32", "float64", "bfloat16",
                            "df64"],
                   help="solve dtype; auto resolves per platform: float32 "
                        "on TPU (the MXU/VPU-native width - float64 runs "
                        "in slow software emulation), float64 on CPU hosts "
                        "(matching the all-f64 reference, CUDACG.cu:216). "
                        "df64 = double-float (hi,lo) f32 pairs: ~f64 "
                        "precision on real TPU hardware (solver.df64; "
                        "plain, Jacobi or Chebyshev PCG; csr/ell/"
                        "shiftell/matrix-free problems; meshes via "
                        "--mesh)")
    p.add_argument("--matrix-free", action="store_true",
                   help="use the matrix-free stencil operator for poisson* "
                        "(default: assembled CSR)")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "xla", "pallas"],
                   help="stencil matvec backend for --matrix-free problems: "
                        "XLA fused adds or the pallas slab-DMA kernel "
                        "(auto picks by grid size)")
    p.add_argument("--engine", default="auto",
                   choices=["auto", "general", "resident", "streaming"],
                   help="solver engine: 'general' is the jitted "
                        "lax.while_loop solver; 'resident' runs the WHOLE "
                        "solve as one VMEM-resident pallas kernel (2D "
                        "stencil, f32, unpreconditioned - ~2.9x faster at "
                        "1M unknowns); 'streaming' is the fused-iteration "
                        "HBM-streaming engine for f32 stencils past the "
                        "VMEM boundary (the 256^3 path, 8 plane-passes/"
                        "iter vs the general solver's ~16); 'auto' picks "
                        "resident, then streaming, when eligible")
    p.add_argument("--method", default="cg",
                   choices=["cg", "cg1", "pipecg", "minres"],
                   help="solver recurrence: textbook CG (the reference's, "
                        "two reductions/iter), Chronopoulos-Gear single-"
                        "reduction CG, Ghysels-Vanroose pipelined CG "
                        "(reduction overlaps the matvec), or MINRES - the "
                        "principled choice for symmetric INDEFINITE "
                        "systems like the reference's own hardcoded "
                        "matrix (quirk Q1; unpreconditioned)")
    p.add_argument("--check-every", type=int, default=1,
                   help="evaluate convergence every k iterations (identical "
                        "iterates; ~30%% faster per iteration at k=32 on "
                        "v5e, up to k-1 extra iterations past convergence)")
    p.add_argument("--format", default="csr", dest="fmt",
                   choices=["csr", "ell", "dia", "shiftell"],
                   help="device layout for assembled-CSR problems: csr "
                        "(gather+segment-sum), ell (padded rectangular "
                        "gather), dia (gather-free shifted FMAs for "
                        "banded matrices), shiftell (the pallas "
                        "lane-gather kernel, f32/f64 values - ~1000x "
                        "faster than csr on 1M-row Poisson, ~67x on "
                        "unstructured FEM after --rcm)")
    p.add_argument("--rcm", action="store_true",
                   help="reverse Cuthill-McKee reorder CSR problems before "
                        "solving (bandwidth/locality; solution is scattered "
                        "back to the original ordering)")
    p.add_argument("--plan", default="even", metavar="auto|even|FILE",
                   help="imbalance-aware partition planning for "
                        "assembled-CSR problems with --mesh > 1 "
                        "(balance.plan_partition): 'auto' enumerates "
                        "(reorder x split) candidates and applies the "
                        "minimizer - balanced-nnz contiguous row ranges "
                        "plus an SPD-preserving symmetric reorder, "
                        "scattered back on output; 'even' (default) is "
                        "the legacy uniform row split; FILE loads a "
                        "saved PartitionPlan JSON.  The applied plan "
                        "and its predicted-vs-measured imbalance ride "
                        "the solve record, --report and the "
                        "partition_plan telemetry event")
    p.add_argument("--repeat", type=int, default=1, metavar="N",
                   help="solve the same system N times through the "
                        "sequence API (parallel.solve_sequence; "
                        "assembled-CSR problems with --mesh > 1, "
                        "general engine): each solve is timed and "
                        "calibrates the runtime machine model "
                        "(telemetry.calibrate - measured gather "
                        "slowdown and net bandwidth, persisted in the "
                        "on-disk cache), and predicted-vs-measured "
                        "drift is tracked per solve.  The reported "
                        "record/timing is the FINAL solve's")
    p.add_argument("--replan", action="store_true",
                   help="with --repeat N >= 2: re-plan solve k+1 on "
                        "the machine model calibrated from solves "
                        "1..k, so the second solve already runs on a "
                        "runtime-corrected partition plan.  The "
                        "kept/switched decision and predicted gain "
                        "ride the 'replan' telemetry event and the "
                        "report's calibration section.  Composes with "
                        "--plan (the first solve's layout)")
    p.add_argument("--recycle", nargs="?", const=0, default=None,
                   type=int, metavar="K",
                   help="Krylov-subspace recycling across --repeat "
                        "solves (solver.recycle): solve 1 carries the "
                        "basis ring + stride-1 flight recorder and "
                        "harvests a K-dimensional Ritz space (bare "
                        "flag: K=8); solves 2..N deflate with it and "
                        "keep accumulating, so measured iters/solve "
                        "falls every solve.  Needs --repeat >= 2 and "
                        "--mesh > 1 on an assembled-CSR problem; "
                        "conflicts with --replan (the space is pinned "
                        "to one partition layout)")
    p.add_argument("--rhs", type=int, default=1, metavar="K",
                   help="solve K right-hand sides as one column-stacked "
                        "batch (solver.many): one matrix sweep and one "
                        "halo exchange per iteration serve every "
                        "column, so the memory-bound SpMV cost "
                        "amortizes across the batch.  The K systems "
                        "share the operator; B is built as A @ X_true "
                        "for a seeded random X_true, so max_abs_error "
                        "is reported per lane.  Single device or "
                        "--mesh > 1 (assembled CSR, general engine, "
                        "--precond none/jacobi); paths that cannot "
                        "batch (resident/streaming engines, df64, "
                        "ring schedules, shiftell format, minres/cg1/"
                        "pipecg, --history, --repeat) refuse rather "
                        "than silently solving one column")
    p.add_argument("--rhs-method", default=None,
                   choices=["batched", "block"], dest="rhs_method",
                   help="batched: K masked independent CG recurrences "
                        "in one loop (each lane bit-matches its "
                        "single-RHS solve at --check-every 1; lanes "
                        "freeze at their own tolerance); block: true "
                        "block-CG (O'Leary) - "
                        "a coupled K-dim Krylov space converges in "
                        "measurably fewer iterations, with Gram "
                        "breakdown falling back to the batched "
                        "recurrence automatically")
    p.add_argument("--phase-profile", nargs="?", const=0, default=None,
                   type=int, metavar="R", dest="phase_profile",
                   help="after a distributed solve, measure its phase "
                        "profile (telemetry.phasetrace): phase-"
                        "isolated step functions built from the "
                        "partitioned operator's own building blocks - "
                        "the halo exchange alone (each gather round "
                        "individually -> per-link bandwidths), the "
                        "local CSR SpMV alone (per shard -> measured "
                        "stall factor), the dot+psum reduction alone - "
                        "each timed over R chained reps (default "
                        "phasetrace.DEFAULT_REPEATS) under the real "
                        "mesh.  Feeds MEASURED Perfetto spans "
                        "(--trace-perfetto span_source=measured), a "
                        "phase_profile event, the report's phase "
                        "section, and a phase-resolved calibration "
                        "that reaches the lstsq2 confident tier from "
                        "this ONE solve (no --repeat needed).  "
                        "Assembled-CSR problems with --mesh > 1, "
                        "general engine")
    p.add_argument("--inject", default=None, metavar="SITE:ITER[:SHARD]",
                   help="deterministic chaos injection (robust."
                        "FaultPlan): corrupt the halo payload, the "
                        "local SpMV output or the reduction scalar at "
                        "a 0-based solver iteration, in-trace via "
                        "lax.cond inside the compiled while_loop "
                        "(e.g. halo:10, spmv:25:2).  The solve exits "
                        "with a typed BREAKDOWN within --check-every "
                        "iterations of the fault; add --recover to "
                        "self-heal.  method=cg, general engine; halo "
                        "site needs --mesh > 1 (it corrupts the "
                        "distributed exchange)")
    p.add_argument("--recover", nargs="?", const=2, default=None,
                   type=int, metavar="N",
                   help="self-healing solve (robust."
                        "solve_with_recovery): on a typed BREAKDOWN, "
                        "restart CG from the last finite iterate up "
                        "to N times (bare flag: 2), emitting "
                        "solve_fault/solve_recovery events.  A "
                        "transient --inject fault disarms on restart; "
                        "the recovered solution matches the "
                        "fault-free solve")
    p.add_argument("--checkpoint", default=None, metavar="PATH",
                   help="run the distributed solve in resumable "
                        "segments, persisting the full per-shard CG "
                        "recurrence state (with layout metadata) to "
                        "PATH after each (utils.checkpoint."
                        "solve_resumable_distributed).  If PATH "
                        "exists the solve RESUMES from it - the exact "
                        "trajectory on the same mesh, or an elastic "
                        "migration with --elastic.  Assembled-CSR "
                        "--mesh > 1, method=cg, general engine")
    p.add_argument("--segment-iters", type=int, default=100,
                   dest="segment_iters", metavar="N",
                   help="iterations per checkpointed segment "
                        "(--checkpoint; default 100)")
    p.add_argument("--elastic", action="store_true",
                   help="allow the checkpointed solve to survive "
                        "TOPOLOGY change (robust.elastic): a "
                        "checkpoint written at a different mesh size/"
                        "plan/exchange is auto-migrated to this run's "
                        "layout (solve_migration event, residual-"
                        "continuity seam contract), and in-run "
                        "watchdog/shard_loss triggers answer with "
                        "checkpoint-now-and-migrate")
    p.add_argument("--watchdog", nargs="?", const=2.0, default=None,
                   type=float, metavar="THRESHOLD",
                   help="straggler watchdog (robust.watchdog): "
                        "profile the partition's measured per-shard "
                        "SpMV / per-link bandwidth between segments "
                        "(telemetry.phasetrace) and emit typed "
                        "shard_degraded events past THRESHOLD x the "
                        "EWMA baseline (bare flag: 2.0); with "
                        "--elastic a degraded shard triggers "
                        "checkpoint-now-and-migrate off its mesh")
    p.add_argument("--keep-last", type=int, default=1,
                   dest="keep_last", metavar="K",
                   help="retain the K most recent checkpoint "
                        "snapshots (PATH, PATH.prev1, ...); a torn/"
                        "corrupt newest file falls back to the "
                        "previous snapshot instead of failing the "
                        "resume (--checkpoint; default 1)")
    p.add_argument("--preempt-after", type=int, default=None,
                   dest="preempt_after", metavar="K",
                   help="chaos drill: kill the checkpointed solve "
                        "after K completed segments (robust."
                        "Preemption) - state is on disk, exit code 3; "
                        "a later identical invocation resumes")
    p.add_argument("--no-validate", action="store_true",
                   dest="no_validate",
                   help="skip the host-side pre-solve finiteness "
                        "check of b and the matrix data (robust."
                        "validate; the check is on by default and "
                        "rejects NaN/Inf inputs loudly instead of "
                        "spinning a poisoned recurrence)")
    p.add_argument("--save-x", default=None, metavar="PATH",
                   dest="save_x",
                   help="np.save the solution vector (or (n, k) "
                        "stack with --rhs) to PATH after the solve - "
                        "how the chaos gate compares a recovered run "
                        "against the fault-free one")
    p.add_argument("--history", action="store_true",
                   help="print per-iteration residual trace")
    p.add_argument("--flight-record", nargs="?", const=1, default=None,
                   type=int, metavar="STRIDE", dest="flight_record",
                   help="carry the convergence flight recorder in the "
                        "solve loop: a fixed-size ring buffer of "
                        "(iteration, ||r||^2, alpha, beta) rows sampled "
                        "every STRIDE iterations (default 1), fetched "
                        "once post-solve - zero host round-trips in the "
                        "hot loop.  Enables solve-health diagnostics "
                        "(stagnation/divergence classification, Ritz "
                        "condition estimate) and makes --history work "
                        "with --mesh > 1 and the resident/streaming "
                        "engines (psum'd residuals; block-granular on "
                        "resident)")
    p.add_argument("--flight-heartbeat", type=int, default=0, metavar="K",
                   dest="flight_heartbeat",
                   help="with --flight-record: post a sampled in-flight "
                        "heartbeat (iteration, ||r||^2) to the host "
                        "every K iterations via an unordered "
                        "jax.debug.callback - progress visibility for "
                        "long solves.  0 (default) compiles the loop "
                        "with NO callback at all; single-device "
                        "general/streaming engines only")
    p.add_argument("--json", action="store_true",
                   help="emit a single JSON record instead of text")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler trace to DIR")
    p.add_argument("--trace-events", default=None, metavar="PATH",
                   dest="trace_events",
                   help="append the solve's telemetry event stream "
                        "(solve_start/engine_selected/comm_cost/"
                        "solve_end, one JSON object per line) to PATH "
                        "- see README 'Observability' for the schema")
    p.add_argument("--report", nargs="?", const="-", default=None,
                   metavar="PATH", dest="report",
                   help="after the solve, emit the unified solve report "
                        "(telemetry.report): status/timing, the "
                        "per-shard rows/nnz/halo-bytes table with "
                        "imbalance factors (--mesh > 1), the roofline "
                        "efficiency verdict, communication totals and "
                        "solve health.  PATH writes the text report to "
                        "a file; bare --report (or '-') prints it; "
                        "with --json the report also rides the record "
                        "as 'solve_report'")
    p.add_argument("--memory-report", action="store_true",
                   dest="memory_report",
                   help="after a --mesh > 1 solve, print the memscope "
                        "device-memory account: per-shard persistent "
                        "bytes (exact - asserted equal to the device "
                        "arrays actually held), the jaxpr-liveness "
                        "transient peak, and the FITS/TIGHT/OVERFLOW "
                        "verdict against the device HBM size; with "
                        "--json the payload rides the record as "
                        "'memory', and --report includes the same "
                        "section")
    p.add_argument("--trace-perfetto", default=None, metavar="PATH",
                   dest="trace_perfetto",
                   help="write a Chrome-trace/Perfetto JSON timeline of "
                        "the solve to PATH (chrome://tracing or "
                        "ui.perfetto.dev loads it): one track per "
                        "shard drawing halo/spmv/reduction phases "
                        "from the static shard accounting scaled to "
                        "measured wall time, one track for host timer "
                        "sections, one residual counter track when "
                        "--flight-record is on")
    p.add_argument("--metrics", action="store_true",
                   help="report the process metrics registry after the "
                        "solve (Prometheus text; embedded as a "
                        "'metrics' object with --json); with --mesh > 1 "
                        "this includes the jaxpr-derived per-iteration "
                        "psum/ppermute/halo-byte gauges")
    p.add_argument("--seed", type=int, default=0)
    return p


def _ensure_virtual_devices(mesh: int) -> None:
    """``--mesh N`` on a CPU host: force N virtual XLA host devices so
    mesh runs work without a pod (the tests' conftest mechanism, made a
    first-class CLI behavior).  No-op when XLA_FLAGS already forces a
    count, or once a backend exists (then ``make_mesh`` reports the
    shortfall as before).  The flag only affects the HOST platform, so
    TPU hosts are unaffected."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={mesh}").strip()


def _configure_backend(args) -> None:
    import jax

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    elif args.device == "tpu":
        pass  # default platform on TPU hosts
    if args.dtype == "auto":
        platform = jax.devices()[0].platform
        args.dtype = "float32" if platform == "tpu" else "float64"
    if args.dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    # df64 = (hi, lo) f32 pairs; problem data built in f32 (exact for the
    # integer-coefficient Poisson/oracle families), solved by solver.df64
    # at ~48-bit precision
    args.df64 = args.dtype == "df64"


def _build_problem(args):
    """Returns (operator, b, x_expected_or_None, description)."""
    import jax.numpy as jnp

    from .models import mmio, poisson, random_spd

    dtype = jnp.dtype("float32" if args.dtype == "df64" else args.dtype)
    rng = np.random.default_rng(args.seed)
    if args.problem == "oracle":
        a, b, x_exp = poisson.oracle_system(dtype=dtype)
        return a, b, x_exp, "reference 3x3 system (CUDACG.cu:74-117)"
    if args.problem == "poisson2d":
        n = args.n
        if args.matrix_free:
            a = poisson.poisson_2d_operator(n, n, dtype=dtype, backend=args.backend)
        else:
            a = poisson.poisson_2d_csr(n, n, dtype=dtype)
        x_true = rng.standard_normal(n * n).astype(dtype)
        return a, a @ jnp.asarray(x_true), x_true, f"2D Poisson {n}x{n}"
    if args.problem == "poisson3d":
        n = args.n
        if args.matrix_free:
            a = poisson.poisson_3d_operator(n, n, n, dtype=dtype, backend=args.backend)
        else:
            a = poisson.poisson_3d_csr(n, n, n, dtype=dtype)
        x_true = rng.standard_normal(n ** 3).astype(dtype)
        return a, a @ jnp.asarray(x_true), x_true, f"3D Poisson {n}^3"
    if args.problem == "random-spd":
        a = random_spd.random_spd_dense(args.n, seed=args.seed, dtype=dtype)
        b = rng.standard_normal(args.n).astype(dtype)
        return a, jnp.asarray(b), None, f"dense random SPD n={args.n}"
    if args.problem == "random-sparse":
        a = random_spd.random_spd_sparse(args.n, seed=args.seed, dtype=dtype)
        b = rng.standard_normal(args.n).astype(dtype)
        return a, jnp.asarray(b), None, f"sparse random SPD n={args.n}"
    if args.problem == "mm":
        if not args.file:
            raise SystemExit("--problem mm requires --file")
        a = mmio.load_matrix_market(args.file, dtype=dtype)
        b = rng.standard_normal(a.shape[0]).astype(dtype)
        return a, jnp.asarray(b), None, f"MatrixMarket {args.file}"
    raise AssertionError(args.problem)


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "lint":
        # graftlint rides the package CLI as a subcommand; the solver
        # flags below don't apply to it, so dispatch before parsing.
        from .analysis.__main__ import main as lint_main

        return lint_main(argv[1:])
    if argv and argv[0] == "serve":
        # the microbatching solver service's workload replay (serve.cli)
        # - its own flag surface, so dispatch before parsing too
        from .serve.cli import main as serve_main

        return serve_main(argv[1:])
    args = build_parser().parse_args(argv)
    if args.mesh > 1 and args.device != "tpu":
        # must run BEFORE the first backend touch (jax reads XLA_FLAGS
        # at client creation)
        _ensure_virtual_devices(args.mesh)
    if args.trace_events or args.metrics or args.report is not None \
            or args.trace_perfetto or args.memory_report:
        from . import telemetry

        if args.trace_events:
            telemetry.configure(args.trace_events)
        if args.metrics or args.report is not None \
                or args.trace_perfetto or args.memory_report:
            # the report/timeline consume the build-time cost walk and
            # the partition-time shard accounting - opt into both
            telemetry.force_active(True)
    if args.precond_degree < 1:
        raise SystemExit(
            f"--precond-degree must be >= 1, got {args.precond_degree}")
    if args.flight_record is not None and args.flight_record < 1:
        raise SystemExit(f"--flight-record stride must be >= 1, got "
                         f"{args.flight_record}")
    if args.flight_heartbeat < 0:
        raise SystemExit(f"--flight-heartbeat must be >= 0, got "
                         f"{args.flight_heartbeat}")
    if args.flight_heartbeat and args.flight_record is None:
        raise SystemExit("--flight-heartbeat requires --flight-record "
                         "(the heartbeat samples the recorder's scalars)")
    if args.flight_heartbeat and (args.mesh > 1
                                  or args.engine == "resident"):
        # never silently drop a flag (ADVICE.md round 5): shard_map'd
        # solves suppress the callback (one per shard per sample would
        # multiply the stream) and the resident kernels never carry it
        raise SystemExit(
            "--flight-heartbeat is single-device general/streaming "
            "only: shard_map'd solves suppress the in-loop callback "
            "and the resident one-kernel engines never carry one. "
            "Drop --flight-heartbeat (the flight record itself still "
            "works), or use --mesh 1 with the general engine.")
    if args.block_size < 1:
        raise SystemExit(f"--block-size must be >= 1, got {args.block_size}")
    if args.backend != "auto" and not args.matrix_free:
        raise SystemExit(
            f"--backend {args.backend} applies to --matrix-free stencil "
            f"problems only (assembled formats pick their own matvec)")
    # The solver converges on max(tol, rtol*||r0||); bf16 is unreachable
    # only when NEITHER term is loose enough.
    if args.dtype == "bfloat16" and not (args.tol >= 1e-3
                                         or args.rtol >= 1e-2):
        raise SystemExit(
            f"--dtype bfloat16 carries ~3 significant digits; a tolerance "
            f"of tol={args.tol:g}/rtol={args.rtol:g} is unreachable and "
            f"would always hit MAXITER. Use --tol >= 1e-3 (or --rtol >= "
            f"1e-2), or --dtype float32.")
    _configure_backend(args)

    import jax

    from .utils import logging as ulog
    from .utils.timing import time_fn

    a, b, x_expected, desc = _build_problem(args)

    rcm_perm = None
    if args.rcm:
        from .models.operators import CSRMatrix

        if not isinstance(a, CSRMatrix):
            raise SystemExit("--rcm applies to assembled CSR problems only")
        rcm_perm = a.rcm_permutation()
        bw_before = a.bandwidth()
        a = a.permuted(rcm_perm)
        b = np.asarray(b)[rcm_perm]
        desc += f" [rcm: bandwidth {bw_before} -> {a.bandwidth()}]"

    if args.csr_comm != "allgather":
        from .models.operators import CSRMatrix

        if args.mesh <= 1:
            raise SystemExit("--csr-comm ring needs --mesh > 1")
        if not isinstance(a, CSRMatrix):
            raise SystemExit(
                "--csr-comm applies to assembled-CSR problems only "
                "(stencils use halo exchange)")

    if args.exchange is not None:
        from .models.operators import CSRMatrix

        if args.mesh <= 1:
            raise SystemExit("--exchange needs --mesh > 1 (the halo "
                             "wire of a distributed CSR solve)")
        if not isinstance(a, CSRMatrix):
            raise SystemExit(
                "--exchange applies to assembled-CSR problems only "
                "(stencil slabs exchange plane halos already)")
        if args.df64:
            raise SystemExit(
                "--exchange does not support --dtype df64 (the "
                "distributed df64 path is the ring-shiftell schedule)")
        if args.engine in ("resident", "streaming"):
            raise SystemExit(
                f"--exchange with --engine {args.engine} is "
                f"unsupported: the one-kernel engines use their own "
                f"stencil partitioners (use --engine general/auto)")
        if args.exchange in ("gather", "allgather") \
                and args.csr_comm != "allgather":
            raise SystemExit(
                f"--exchange {args.exchange} conflicts with --csr-comm "
                f"{args.csr_comm} (the ring schedules rotate full "
                f"x-blocks; drop one of the two flags)")
        desc += f" [exchange: {args.exchange}]"

    # Imbalance-aware partition planning (balance): resolved HERE, not
    # inside the solver, so the chosen lane can ride the description,
    # the record and the report.  Composes with --rcm (the plan sees,
    # and its candidate reorders permute, the post-RCM matrix).
    plan_obj = None
    plan_model = None   # the MachineModel that priced plan_obj, if any
    if args.plan != "even":
        from .models.operators import CSRMatrix

        if args.mesh <= 1:
            raise SystemExit("--plan needs --mesh > 1 (partition "
                             "planning rebalances a device mesh)")
        if not isinstance(a, CSRMatrix):
            raise SystemExit(
                "--plan applies to assembled-CSR problems only "
                "(stencil slabs are uniform by construction)")
        if args.engine in ("resident", "streaming"):
            raise SystemExit(
                f"--plan with --engine {args.engine} is unsupported: "
                f"the distributed one-kernel engines use their own "
                f"stencil partitioners (use --engine general/auto)")
        from .balance import PartitionPlan, plan_partition

        if args.plan == "auto":
            # same model preference as the API path (resolve_plan): a
            # fresh + confident on-disk calibration for this backend/
            # host prices the plan; absent one, the reference table.
            # The exchange lane the planner searches/pins mirrors the
            # solve's (dist_cg._plan_exchange_hint), so a --exchange
            # pin never gets a plan scored for a different wire.
            from .parallel.dist_cg import _plan_exchange_hint
            from .telemetry import calibrate as _tcal

            plan_model = _tcal.preferred_model()
            plan_obj = plan_partition(
                a, args.mesh, model=plan_model,
                exchange=_plan_exchange_hint(args.csr_comm,
                                             args.exchange))
        else:
            try:
                plan_obj = PartitionPlan.load(args.plan)
            except (OSError, ValueError, KeyError, TypeError) as e:
                raise SystemExit(f"--plan {args.plan}: {e}")
        try:
            if plan_obj.n_shards != args.mesh:
                raise ValueError(
                    f"plan targets {plan_obj.n_shards} shards but "
                    f"--mesh is {args.mesh}")
            if plan_obj.exchange == "gather" \
                    and (args.csr_comm in ("ring", "ring-shiftell")
                         or args.exchange == "ring"):
                raise ValueError(
                    f"plan was scored for the gather halo exchange "
                    f"but the requested ring schedule rotates full "
                    f"x-blocks (re-plan for the ring wire, or drop "
                    f"the ring flag)")
            plan_obj.validate_for(a)
        except ValueError as e:
            raise SystemExit(f"--plan {args.plan}: {e}")
        desc += f" [plan: {plan_obj.label}]"

    # Solve sequences (--repeat/--replan): the runtime-calibration +
    # replan loop rides the general distributed CSR path only - the
    # one with a partition to re-plan.
    if args.repeat < 1:
        raise SystemExit(f"--repeat must be >= 1, got {args.repeat}")
    if args.replan and args.repeat < 2:
        raise SystemExit("--replan needs --repeat >= 2 (solve k+1 "
                         "re-plans on the model calibrated from solve "
                         "k; a single solve has no later solve to "
                         "correct)")
    if args.repeat > 1:
        from .models.operators import CSRMatrix

        if args.mesh <= 1:
            raise SystemExit("--repeat needs --mesh > 1 (the sequence "
                             "API calibrates and re-plans a "
                             "distributed partition)")
        if not isinstance(a, CSRMatrix):
            raise SystemExit("--repeat applies to assembled-CSR "
                             "problems only (stencil slabs are uniform "
                             "by construction - nothing to replan)")
        if args.engine in ("resident", "streaming"):
            raise SystemExit(f"--repeat with --engine {args.engine} is "
                             f"unsupported: the one-kernel engines use "
                             f"their own partitioners (use --engine "
                             f"general/auto)")
        if args.dtype == "df64":
            raise SystemExit("--repeat does not support --dtype df64 "
                             "(the sequence API rides the f32/f64 "
                             "general distributed path)")
        if args.precond == "bjacobi":
            # the single-solve path refuses this inside run(); the
            # sequence path dispatches solve_distributed directly, so
            # restate the refusal here rather than leak a traceback
            raise SystemExit(
                "--precond bjacobi is single-device only (use jacobi "
                "or chebyshev with --mesh)")

    # Krylov recycling (--recycle): the repeat-solve deflation loop.
    # Same never-silently-drop rule as every other flag: any path that
    # cannot carry the basis ring or the deflated recurrence refuses
    # loudly here.
    if args.recycle is not None:
        if args.recycle < 0:
            raise SystemExit(f"--recycle K must be >= 0, got "
                             f"{args.recycle} (0/bare flag = the "
                             f"default space dimension)")
        if args.repeat < 2:
            raise SystemExit(
                "--recycle needs --repeat >= 2 (solve 1 harvests the "
                "space a later solve deflates with; a single solve "
                "has nothing to recycle into)")
        if args.replan:
            raise SystemExit(
                "--recycle with --replan is unsupported (the "
                "harvested space lives in one partition layout; a "
                "replan that switched layouts would invalidate it "
                "mid-sequence)")
        if args.method != "cg":
            raise SystemExit(
                f"--recycle rides --method cg only (got "
                f"{args.method}): the deflation projects the textbook "
                f"direction recurrence")
        if args.rhs > 1:
            raise SystemExit(
                "--recycle with --rhs is unsupported on the CLI (the "
                "serve subcommand's --recycle is the many-RHS "
                "recycling lane)")
        if args.inject is not None or args.recover is not None:
            raise SystemExit(
                "--recycle with --inject/--recover is unsupported (a "
                "poisoned solve must not seed the recycled space)")
        if args.csr_comm in ("ring", "ring-shiftell") \
                or args.exchange == "ring":
            raise SystemExit(
                "--recycle needs the allgather/gather halo wires "
                "(the ring schedules carry neither the sharded "
                "projection operands nor the basis ring)")
        if args.flight_record is not None and args.flight_record != 1:
            raise SystemExit(
                f"--recycle needs a stride-1 flight record (got "
                f"--flight-record {args.flight_record}): the harvest "
                f"assembles the Lanczos tridiagonal from consecutive "
                f"alpha/beta rows")

    # Phase profiling (--phase-profile): the measured per-shard
    # per-phase timing runs on the general distributed CSR lanes only
    # (they are the operators whose building blocks the profiler
    # isolates).  Same never-silently-drop rule as every other flag.
    if args.phase_profile is not None:
        from .models.operators import CSRMatrix

        if args.phase_profile < 0:
            raise SystemExit(f"--phase-profile reps must be >= 0, got "
                             f"{args.phase_profile} (0/bare flag = the "
                             f"default rep count)")
        if args.mesh <= 1:
            raise SystemExit("--phase-profile needs --mesh > 1 (it "
                             "times the distributed halo/spmv/"
                             "reduction phases)")
        if not isinstance(a, CSRMatrix):
            raise SystemExit(
                "--phase-profile applies to assembled-CSR problems "
                "(the partitioned-operator lanes); stencil slabs fuse "
                "their phases in one kernel")
        if args.engine in ("resident", "streaming"):
            raise SystemExit(
                f"--phase-profile with --engine {args.engine} is "
                f"unsupported: the one-kernel engines fuse their "
                f"phases on device (use --engine general/auto)")
        if args.df64:
            raise SystemExit(
                "--phase-profile does not support --dtype df64 (the "
                "distributed df64 path is the fused ring-shiftell "
                "schedule)")
        if args.csr_comm == "ring-shiftell":
            raise SystemExit(
                "--phase-profile does not support --csr-comm "
                "ring-shiftell (the pallas slab kernel fuses its "
                "phases; use the csr ring lane)")
        if args.rhs > 1:
            raise SystemExit(
                "--phase-profile with --rhs is unsupported (the "
                "profiler times single-vector phases, which cannot be "
                "honestly compared against a k-column solve's "
                "per-iteration wall; profile a single-RHS solve of "
                "the same system)")

    # Many-RHS batching (--rhs K): the refusal matrix.  Every path that
    # cannot carry a column stack refuses LOUDLY here - silently
    # solving column 0 of a K-column request would be a wrong answer
    # with a green exit code (same never-silently-drop rule as
    # --history/--flight-record/--replan).
    if args.rhs < 1:
        raise SystemExit(f"--rhs must be >= 1, got {args.rhs}")
    if args.rhs_method is not None and args.rhs <= 1:
        raise SystemExit(
            f"--rhs-method {args.rhs_method} needs --rhs K > 1 (it "
            f"selects the batched recurrence; a single RHS runs the "
            f"ordinary --method solver)")
    if args.rhs > 1:
        args.rhs_method = args.rhs_method or "batched"
        from .models.operators import CSRMatrix

        if args.df64:
            raise SystemExit(
                "--rhs does not support --dtype df64 (the double-float "
                "solvers carry (hi, lo) pair recurrences with no "
                "batched tier yet; solve the columns sequentially)")
        if args.method != "cg":
            raise SystemExit(
                f"--rhs batches the textbook CG recurrence only; "
                f"--method {args.method} has no batched variant. "
                f"Pick the batched recurrence with --rhs-method "
                f"batched|block instead")
        if args.engine in ("resident", "streaming"):
            raise SystemExit(
                f"--rhs with --engine {args.engine} is unsupported: "
                f"the one-kernel engines hold a single x resident per "
                f"chip (use --engine general/auto)")
        if args.history:
            raise SystemExit(
                "--history with --rhs is unsupported (K dense traces); "
                "use --flight-record for the per-lane ring-buffer "
                "trace")
        if args.repeat > 1:
            raise SystemExit(
                "--repeat with --rhs is unsupported (the calibrate-"
                "and-replan sequence API is single-RHS)")
        if args.csr_comm != "allgather" or args.exchange == "ring":
            raise SystemExit(
                "--rhs needs the allgather/gather halo wires (the "
                "ring schedules rotate single x-blocks; drop "
                "--csr-comm ring / --exchange ring)")
        if args.fmt == "shiftell":
            raise SystemExit(
                "--rhs with --format shiftell is unsupported (the "
                "pallas lane-gather kernel consumes one x plane; use "
                "--format csr/ell/dia)")
        if args.flight_record is not None and args.rhs_method == "block":
            raise SystemExit(
                "--flight-record with --rhs-method block is "
                "unsupported (block-CG's recurrence scalars are KxK "
                "matrices, not per-lane pairs; use --rhs-method "
                "batched)")
        if args.flight_heartbeat:
            raise SystemExit(
                "--flight-heartbeat with --rhs is unsupported (the "
                "batched loop carries no in-loop callback; the "
                "per-lane flight record itself works - drop the "
                "heartbeat)")
        if args.mesh > 1:
            if not isinstance(a, CSRMatrix):
                raise SystemExit(
                    "--rhs with --mesh > 1 supports assembled-CSR "
                    "problems only (stencil slabs batch on a single "
                    "device; drop --matrix-free or --mesh)")
            if args.precond not in (None, "jacobi"):
                raise SystemExit(
                    f"--rhs with --mesh > 1 supports --precond jacobi "
                    f"or none (got {args.precond}: its application is "
                    f"single-vector on a mesh)")
        elif args.precond == "bjacobi" and args.rhs_method == "block":
            # bjacobi's dense block solve vmaps fine lane-wise, but
            # block-CG couples lanes through the Gram solve - keep the
            # tested surface: batched only
            raise SystemExit(
                "--precond bjacobi with --rhs-method block is "
                "unsupported (use --rhs-method batched)")

    # Chaos injection / recovery (--inject / --recover): the robust/
    # harness rides the general textbook-CG lanes.  Same
    # never-silently-drop rule as every other flag: any path that
    # cannot carry the fault (or the restart loop) refuses loudly.
    fault_plan = None
    recover_policy = None
    if args.inject is not None:
        from .models.operators import CSRMatrix
        from .robust import FaultPlan

        try:
            fault_plan = FaultPlan.parse(args.inject)
        except ValueError as e:
            raise SystemExit(f"--inject {args.inject}: {e}")
        if args.method != "cg":
            raise SystemExit(
                f"--inject rides --method cg only (got "
                f"{args.method}): the chaos harness drills the "
                f"textbook recurrence")
        if args.df64:
            raise SystemExit("--inject does not support --dtype df64 "
                             "(the double-float recurrences carry no "
                             "injection sites yet)")
        if args.engine in ("resident", "streaming"):
            raise SystemExit(
                f"--inject with --engine {args.engine} is "
                f"unsupported: the one-kernel engines carry no "
                f"injection sites (use --engine general/auto)")
        if args.repeat > 1:
            raise SystemExit("--inject with --repeat is unsupported "
                             "(a poisoned solve must not feed the "
                             "calibration loop)")
        if args.csr_comm != "allgather" or args.exchange == "ring":
            raise SystemExit(
                "--inject needs the allgather/gather halo wires "
                "(the ring schedules carry no injection hook; drop "
                "--csr-comm ring / --exchange ring)")
        if args.rhs > 1 and (args.rhs_method or "batched") == "block":
            raise SystemExit(
                "--inject with --rhs-method block is unsupported "
                "(block-CG's Gram-collapse fallback would mask the "
                "fault; use --rhs-method batched)")
        if fault_plan.site == "halo" and args.mesh <= 1:
            raise SystemExit(
                "--inject halo:... needs --mesh > 1 (it corrupts the "
                "distributed halo exchange payload; single-device "
                "solves have no wire - use spmv: or reduction:)")
        if fault_plan.shard >= max(args.mesh, 1):
            raise SystemExit(
                f"--inject targets shard {fault_plan.shard} but "
                f"--mesh is {args.mesh}")
        if args.mesh > 1 and not isinstance(a, CSRMatrix):
            raise SystemExit(
                "--inject with --mesh > 1 supports assembled-CSR "
                "problems only (stencil slabs carry no injection "
                "hook; drop --matrix-free)")
        desc += f" [inject: {args.inject}]"
    if args.recover is not None:
        from .robust import RecoveryPolicy

        if args.recover < 0:
            raise SystemExit(f"--recover must be >= 0, got "
                             f"{args.recover}")
        if args.method != "cg":
            raise SystemExit(f"--recover rides --method cg only "
                             f"(got {args.method})")
        if args.df64 or args.engine in ("resident", "streaming") \
                or args.repeat > 1:
            raise SystemExit(
                "--recover is unsupported with --dtype df64, "
                "--engine resident/streaming and --repeat (the "
                "restart loop re-dispatches the general cg path)")
        if args.rhs > 1:
            raise SystemExit(
                "--recover with --rhs is unsupported (the restart "
                "loop is single-RHS; the serve retry policy is the "
                "many-RHS recovery lane)")
        if args.mesh > 1 and (args.csr_comm != "allgather"
                              or args.exchange == "ring"):
            raise SystemExit(
                "--recover needs the allgather/gather halo wires on "
                "a mesh (drop --csr-comm ring/ring-shiftell / "
                "--exchange ring): a restart seeded from the last "
                "finite iterate re-dispatches with x0, which the "
                "ring schedules do not carry")
        recover_policy = RecoveryPolicy(max_restarts=args.recover)
        desc += f" [recover: {args.recover}]"

    # Elastic checkpointed solves (--checkpoint): the resumable
    # distributed lane with layout metadata, retention, and (with
    # --elastic) cross-mesh migration.  Same never-silently-drop rule:
    # every path that cannot carry the segment loop refuses loudly.
    if args.checkpoint is not None:
        from .models.operators import CSRMatrix

        if args.mesh <= 1:
            raise SystemExit("--checkpoint needs --mesh > 1 (the "
                             "resumable lane persists the per-shard "
                             "distributed recurrence state; single-"
                             "device resumable solves ride the "
                             "utils.checkpoint.solve_resumable API)")
        if not isinstance(a, CSRMatrix):
            raise SystemExit(
                "--checkpoint supports assembled-CSR problems only "
                "(stencil slabs carry no checkpointable distributed "
                "recurrence yet; drop --matrix-free)")
        if args.method != "cg":
            raise SystemExit(f"--checkpoint rides --method cg only "
                             f"(got {args.method})")
        if args.df64 or args.engine in ("resident", "streaming"):
            raise SystemExit(
                "--checkpoint is unsupported with --dtype df64 and "
                "--engine resident/streaming (the segment loop "
                "re-dispatches the general distributed cg path)")
        if args.csr_comm != "allgather" or args.exchange == "ring":
            raise SystemExit(
                "--checkpoint needs the allgather/gather halo wires "
                "(the ring schedules carry no checkpointable state; "
                "drop --csr-comm ring / --exchange ring)")
        if args.rhs > 1 or args.repeat > 1 or args.recover is not None \
                or args.recycle is not None:
            raise SystemExit(
                "--checkpoint is unsupported with --rhs/--repeat/"
                "--recover/--recycle (the segment loop is a "
                "single-RHS resumable solve; serve retries and the "
                "calibration sequence are separate lanes)")
        if args.history or args.flight_record is not None:
            raise SystemExit(
                "--checkpoint with --history/--flight-record is "
                "unsupported (the recorder would cover only the "
                "final segment and silently misreport the solve)")
        if args.segment_iters < 1:
            raise SystemExit(f"--segment-iters must be >= 1, got "
                             f"{args.segment_iters}")
        if args.keep_last < 1:
            raise SystemExit(f"--keep-last must be >= 1, got "
                             f"{args.keep_last}")
        if args.preempt_after is not None and args.preempt_after < 1:
            raise SystemExit(f"--preempt-after must be >= 1, got "
                             f"{args.preempt_after}")
        desc += " [checkpoint]" + (" [elastic]" if args.elastic else "")
    else:
        for flag, name in ((args.elastic, "--elastic"),
                           (args.watchdog is not None, "--watchdog"),
                           (args.keep_last > 1, "--keep-last"),
                           (args.preempt_after is not None,
                            "--preempt-after")):
            if flag:
                raise SystemExit(f"{name} needs --checkpoint PATH "
                                 f"(it governs the resumable segment "
                                 f"loop)")
    if fault_plan is not None and fault_plan.site in (
            "shard_slow", "shard_loss"):
        if args.checkpoint is None:
            raise SystemExit(
                f"--inject {fault_plan.site}:... is a host-level "
                f"elastic drill - it needs --checkpoint PATH (and "
                f"--elastic to migrate)")
        if fault_plan.site == "shard_slow" and args.watchdog is None:
            raise SystemExit(
                "--inject shard_slow:... drills the straggler "
                "watchdog - add --watchdog [THRESHOLD]")
        if fault_plan.site == "shard_loss" and not args.elastic:
            raise SystemExit(
                "--inject shard_loss:... needs --elastic (a lost "
                "shard can only be survived by migrating off it)")

    # Loud pre-solve validation (robust.validate): reject non-finite
    # b/matrix data HERE, before any partitioning or compile - a NaN
    # input would otherwise spin the recurrence to its first health
    # check and report a BREAKDOWN that was knowable for free.
    if not args.no_validate:
        from .robust.validate import check_finite_problem

        try:
            check_finite_problem(a, b)
        except ValueError as e:
            raise SystemExit(str(e))

    # df64 compatibility checks run BEFORE the format conversion below:
    # a doomed combination must fail fast, not after seconds of host-side
    # shift-ELL packing at 1M rows.
    if args.df64:
        from .models.operators import (
            CSRMatrix as _CSR,
            ELLMatrix as _ELL,
            Stencil2D as _S2,
            Stencil3D as _S3,
        )

        bad = None
        if args.mesh > 1 and not isinstance(a, (_CSR, _S2, _S3)):
            bad = ("--mesh > 1 with this operator (distributed df64 "
                   "supports matrix-free stencils and assembled CSR)")
        elif args.mesh > 1 and args.fmt != "csr":
            bad = (f"--format {args.fmt} with --mesh > 1 (distributed "
                   f"CSR uses the df64 ring-shiftell schedule directly)")
        elif args.precond not in (None, "jacobi", "chebyshev", "mg"):
            bad = (f"--precond {args.precond} (None, jacobi, chebyshev "
                   f"or mg only)")
        elif args.precond == "mg" and not isinstance(a, (_S2, _S3)):
            bad = ("--precond mg on a non-stencil operator (the "
                   "geometric hierarchy needs a matrix-free grid)")
        elif args.precond in ("chebyshev", "mg") and args.method != "cg":
            bad = f"--precond {args.precond} with --method != cg"
        elif args.fmt == "dia":
            bad = "--format dia (csr/ell/shiftell/matrix-free only)"
        elif not isinstance(a, (_CSR, _ELL, _S2, _S3)):
            bad = (f"{type(a).__name__} operators (dense df64 would need "
                   f"error-free MXU accumulation)")
        if bad:
            raise SystemExit(f"--dtype df64 does not support {bad}")
        desc += " [df64]"

    if args.fmt != "csr":
        from .models.operators import CSRMatrix

        if not isinstance(a, CSRMatrix):
            raise SystemExit(
                f"--format {args.fmt} applies to assembled CSR problems "
                f"only")
        if args.mesh > 1:
            raise SystemExit(f"--format {args.fmt} is single-device only "
                             f"(distributed CSR uses its own partition)")
        # df64 + shiftell packs the double-float (hi, lo) sheet planes
        # for the pallas df64 lane-gather kernel
        conv = {"dia": a.to_dia, "ell": a.to_ell,
                "shiftell": (a.to_shiftell_df64 if args.df64
                             else a.to_shiftell)}[args.fmt]
        try:
            a = conv()
        except ValueError as e:
            raise SystemExit(f"--format {args.fmt}: {e}")
        desc += f" [{args.fmt}]"

    # The convergence flight recorder (telemetry.flight): a fixed-size
    # stride-decimated ring of (iteration, ||r||^2, alpha, beta) rows
    # carried in the solve loop, fetched once post-solve.
    flight_cfg = None
    if args.flight_record is not None:
        if args.method == "minres":
            raise SystemExit(
                "--flight-record does not support --method minres (its "
                "Lanczos recurrence has no CG alpha/beta scalars to "
                "record; use --history for its per-iteration trace)")
        if args.df64 and args.method != "cg":
            raise SystemExit(
                f"--flight-record with --dtype df64 supports --method "
                f"cg only (got --method {args.method}); use --history "
                f"for the variants' dense trace")
        from .telemetry.flight import FlightConfig

        flight_cfg = FlightConfig.for_solve(
            args.maxiter, stride=args.flight_record,
            heartbeat=args.flight_heartbeat)

    # The distributed resident/streaming engines keep every iteration
    # on device; without the flight recorder there is no per-iteration
    # host visibility to build a --history trace from.  With
    # --flight-record the trace rides the recorder (psum'd residuals -
    # per-iteration on streaming, check-block granular on resident), so
    # the refusal only applies to the bare flag (ADVICE.md round 5:
    # never silently drop it).
    if args.history and args.mesh > 1 \
            and args.engine in ("resident", "streaming") \
            and flight_cfg is None:
        raise SystemExit(
            f"--history with --engine {args.engine} --mesh {args.mesh} "
            f"needs the convergence flight recorder: the distributed "
            f"one-kernel-per-chip solves keep every iteration on device "
            f"and record no dense residual trace. Add --flight-record "
            f"[STRIDE] to carry the on-device ring buffer (the "
            f"decimated trace prints through it), or use --engine "
            f"general for a dense traced distributed solve.")
    if args.engine == "resident":
        if args.mesh > 1 and (args.precond not in (None, "chebyshev")
                              or args.method != "cg" or args.df64):
            raise SystemExit("--engine resident with --mesh > 1 runs the "
                             "distributed one-kernel-per-chip solve: "
                             "f32 --method cg with --precond chebyshev "
                             "or none")
        if (args.precond not in (None, "chebyshev")
                or args.method not in ("cg", "cg1")
                or (args.method == "cg1" and args.precond is not None)):
            raise SystemExit("--engine resident supports --method cg "
                             "(--precond chebyshev or none) or the "
                             "unpreconditioned --method cg1 single-"
                             "reduction kernel (--history and "
                             "--flight-record are fine: both ride the "
                             "kernel's check-block-granular trace)")
    if args.method == "minres":
        if args.precond is not None:
            raise SystemExit(
                "--method minres is unpreconditioned (preconditioned "
                "MINRES needs an SPD preconditioner and a different "
                "inner product; use a CG method with --precond)")
        if args.df64 and args.mesh > 1:
            raise SystemExit(
                "--method minres --dtype df64 is single-device (the "
                "distributed df64 backend carries the CG recurrences; "
                "drop --mesh or use f32 minres on the mesh)")
    if args.engine == "streaming":
        if args.mesh > 1 and (args.precond is not None
                              or args.method != "cg"):
            raise SystemExit("--engine streaming with --mesh > 1 runs "
                             "the distributed fused-slab solve: "
                             "unpreconditioned --method cg only (the "
                             "streamed Chebyshev path is single-device)")
        if args.precond not in (None, "chebyshev") or args.method != "cg":
            raise SystemExit("--engine streaming supports --method cg "
                             "with --precond chebyshev or none "
                             "(--history and --flight-record are fine: "
                             "the trace is per-iteration)")
        if args.df64:
            raise SystemExit("--engine streaming is float32-only "
                             "(--dtype df64 routes through the general "
                             "or resident df64 solvers)")

    def _build_precond():
        """The single-device preconditioner for the general solvers
        (shared by the single-RHS general path and the many-RHS
        batched path - both apply M through the same operator
        interface)."""
        from .models.operators import JacobiPreconditioner
        from .models.precond import (
            BlockJacobiPreconditioner,
            ChebyshevPreconditioner,
        )

        if args.precond == "jacobi":
            return JacobiPreconditioner.from_operator(a)
        if args.precond == "chebyshev":
            return ChebyshevPreconditioner.from_operator(
                a, degree=args.precond_degree)
        if args.precond == "bjacobi":
            return BlockJacobiPreconditioner.from_operator(
                a, block_size=args.block_size)
        if args.precond == "mg":
            from .models.multigrid import MultigridPreconditioner
            from .models.operators import Stencil2D, Stencil3D

            if not isinstance(a, (Stencil2D, Stencil3D)):
                raise SystemExit(
                    "--precond mg needs a stencil operator: use a "
                    "poisson* problem with --matrix-free")
            return MultigridPreconditioner.from_operator(a)
        return None

    # The many-RHS system: K columns sharing the (final, post-rcm/
    # format) operator.  B = A @ X_true for a seeded X_true, so every
    # lane has a known solution and the record carries per-lane
    # max_abs_error (the lint gate's acceptance check).  Errors are
    # permutation-invariant (max over entries), so --rcm composes.
    if args.rhs > 1:
        import jax.numpy as _jnp

        rhs_rng = np.random.default_rng(args.seed + 202406)
        b_np = np.asarray(b)
        x_expected = rhs_rng.standard_normal(
            (int(a.shape[0]), args.rhs)).astype(b_np.dtype)
        b = np.asarray(a.matmat(_jnp.asarray(x_expected)))
        desc += f" [rhs: {args.rhs} x {args.rhs_method}]"

    recovery_box = [None]   # RecoveredResult of the last --recover run

    def run():
        if recover_policy is not None:
            from .robust import solve_with_recovery

            if args.mesh > 1:
                from .parallel import make_mesh

                rr = solve_with_recovery(
                    a, b, mesh=make_mesh(args.mesh),
                    policy=recover_policy, inject=fault_plan,
                    tol=args.tol, rtol=args.rtol,
                    maxiter=args.maxiter,
                    validate=False,   # CLI validated once pre-dispatch
                    preconditioner=args.precond,
                    precond_degree=args.precond_degree,
                    record_history=args.history, method=args.method,
                    check_every=args.check_every,
                    csr_comm=args.csr_comm, flight=flight_cfg,
                    plan=plan_obj, exchange=args.exchange)
            else:
                rr = solve_with_recovery(
                    a, b, policy=recover_policy, inject=fault_plan,
                    tol=args.tol, rtol=args.rtol,
                    maxiter=args.maxiter,
                    validate=False,   # CLI validated once pre-dispatch
                    m=_build_precond(),
                    record_history=args.history,
                    check_every=args.check_every)
            recovery_box[0] = rr
            return rr.result
        if args.rhs > 1:
            if args.mesh > 1:
                from .parallel import make_mesh, solve_distributed_many

                return solve_distributed_many(
                    a, b, mesh=make_mesh(args.mesh), tol=args.tol,
                    rtol=args.rtol, maxiter=args.maxiter,
                    preconditioner=args.precond,
                    method=args.rhs_method,
                    check_every=args.check_every, flight=flight_cfg,
                    plan=plan_obj, exchange=args.exchange,
                    inject=fault_plan)
            from .solver import solve_many

            return solve_many(a, b, tol=args.tol, rtol=args.rtol,
                              maxiter=args.maxiter, m=_build_precond(),
                              method=args.rhs_method,
                              check_every=args.check_every,
                              flight=flight_cfg, fault=fault_plan)
        if args.df64:
            if args.mesh > 1:
                from .parallel import make_mesh, solve_distributed_df64

                return solve_distributed_df64(
                    a, np.asarray(b, dtype=np.float64),
                    mesh=make_mesh(args.mesh), tol=args.tol,
                    rtol=args.rtol, maxiter=args.maxiter,
                    preconditioner=args.precond,
                    precond_degree=args.precond_degree,
                    record_history=args.history,
                    check_every=args.check_every, method=args.method,
                    flight=flight_cfg, plan=plan_obj)
            if args.engine in ("auto", "resident") and args.mesh == 1:
                from .models.operators import _pallas_interpret
                from .solver.resident import (
                    cg_resident_df64,
                    supports_resident_df64,
                )

                # auto + --flight-record keeps the per-iteration general
                # df64 recorder; an explicit --engine resident records
                # at the kernel's check-block granularity (the block
                # trace adapts into the recorder layout post-solve)
                eligible = (supports_resident_df64(
                                a,
                                preconditioned=args.precond == "chebyshev")
                            and args.precond in (None, "chebyshev")
                            and args.method == "cg"
                            and (not args.history
                                 or args.engine == "resident")
                            and (flight_cfg is None
                                 or args.engine == "resident")
                            and (args.engine == "resident"
                                 or _jax_backend_is_tpu()))
                if args.engine == "resident" and not eligible:
                    raise SystemExit(
                        f"--engine resident --dtype df64 does not support "
                        f"{type(a).__name__} at this size (needs a 2D/3D "
                        f"stencil whose df64 working set fits VMEM)")
                if eligible:
                    return cg_resident_df64(
                        a, np.asarray(b, dtype=np.float64), tol=args.tol,
                        rtol=args.rtol, maxiter=args.maxiter,
                        check_every=args.check_every,
                        record_history=(args.history
                                        or flight_cfg is not None),
                        preconditioner=args.precond,
                        precond_degree=args.precond_degree,
                        interpret=_pallas_interpret())
            from .solver.df64 import cg_df64

            return cg_df64(a, np.asarray(b, dtype=np.float64),
                           tol=args.tol, rtol=args.rtol,
                           maxiter=args.maxiter,
                           preconditioner=args.precond,
                           precond_degree=args.precond_degree,
                           record_history=args.history,
                           check_every=args.check_every,
                           method=args.method, flight=flight_cfg)
        if args.mesh > 1:
            from .parallel import make_mesh, solve_distributed
            from .models.operators import CSRMatrix, Stencil2D, Stencil3D

            if not isinstance(a, (CSRMatrix, Stencil2D, Stencil3D)):
                raise SystemExit(
                    "--mesh > 1 supports CSR and stencil problems only")
            if args.engine == "resident":
                # the one-kernel-per-chip distributed resident solve
                # (in-kernel RDMA halos + allreduces, in-kernel
                # Chebyshev); scope enforced by the engine gate above
                from .parallel import solve_distributed_resident

                m_dr = None
                if args.precond == "chebyshev":
                    from .models.precond import ChebyshevPreconditioner

                    m_dr = ChebyshevPreconditioner.from_operator(
                        a, degree=args.precond_degree)
                try:
                    return solve_distributed_resident(
                        a, b, mesh=make_mesh(args.mesh), tol=args.tol,
                        rtol=args.rtol, maxiter=args.maxiter,
                        check_every=args.check_every, m=m_dr,
                        record_history=args.history, flight=flight_cfg)
                except (TypeError, ValueError) as e:
                    raise SystemExit(f"--engine resident --mesh "
                                     f"{args.mesh}: {e}")
            if args.engine == "streaming":
                from .parallel import solve_distributed_streaming

                try:
                    return solve_distributed_streaming(
                        a, b, mesh=make_mesh(args.mesh), tol=args.tol,
                        rtol=args.rtol, maxiter=args.maxiter,
                        check_every=args.check_every, flight=flight_cfg)
                except (TypeError, ValueError) as e:
                    raise SystemExit(f"--engine streaming --mesh "
                                     f"{args.mesh}: {e}")
            if args.precond == "bjacobi":
                raise SystemExit(
                    "--precond bjacobi is single-device only (use jacobi "
                    "or chebyshev with --mesh)")
            return solve_distributed(
                a, b, mesh=make_mesh(args.mesh), tol=args.tol,
                rtol=args.rtol, maxiter=args.maxiter,
                preconditioner=args.precond,
                precond_degree=args.precond_degree,
                record_history=args.history, method=args.method,
                check_every=args.check_every, csr_comm=args.csr_comm,
                flight=flight_cfg, plan=plan_obj,
                exchange=args.exchange, inject=fault_plan,
                # the CLI already ran the O(nnz) finiteness scan once,
                # pre-dispatch (or the user opted out): re-scanning
                # inside every warmup/timed/repeat dispatch would only
                # distort the timings
                validate=False)
        if args.engine in ("auto", "resident"):
            from .models.operators import _pallas_interpret
            from .solver.resident import (
                cg_resident,
                resident_eligible,
                supports_resident,
            )

            # "auto" takes the resident engine only on a compiled TPU
            # backend: off-TPU the kernel would run in pallas interpret
            # mode, orders of magnitude slower than the jitted general
            # solver.  An EXPLICIT --engine resident still honors the
            # request anywhere (interpret mode off-TPU - correctness
            # checks, not speed).  Eligibility itself is the shared
            # solver.resident.resident_eligible predicate - one source
            # of truth with solve(engine=...).
            # Cheap gates first - the Chebyshev construction below runs
            # a 30-matvec power iteration, so it must not be built for
            # solves that cannot take the resident path anyway.
            # resident_eligible stays the final authority.
            # --history is resident-eligible only on an EXPLICIT
            # --engine resident (block-granular trace, user opted in);
            # auto keeps history on the general solver's per-iteration
            # granularity - same rule as solve(engine=...).
            history_ok = not args.history or args.engine == "resident"
            # same rule for the flight recorder: the kernel trace is
            # check-block granular, so auto keeps a requested recorder
            # on the general solver's per-iteration granularity; an
            # explicit --engine resident adapts the block trace
            flight_ok = flight_cfg is None or args.engine == "resident"
            cheap_ok = (args.precond in (None, "chebyshev")
                        and args.method in ("cg", "cg1") and history_ok
                        and flight_ok
                        and fault_plan is None
                        and (args.engine == "resident"
                             or _jax_backend_is_tpu())
                        and supports_resident(
                            a, preconditioned=args.precond == "chebyshev"))
            m_res = None
            if cheap_ok and args.precond == "chebyshev":
                from .models.precond import ChebyshevPreconditioner

                m_res = ChebyshevPreconditioner.from_operator(
                    a, degree=args.precond_degree)
            eligible = cheap_ok and resident_eligible(
                a, b, m_res, method=args.method,
                record_history=(args.history
                                and args.engine != "resident"))
            if args.engine == "resident" and not eligible:
                raise SystemExit(
                    f"--engine resident does not support "
                    f"{type(a).__name__} at this size/dtype (needs a "
                    f"float32 2D/3D stencil whose CG working set fits "
                    f"VMEM and a float32 rhs; try --problem poisson2d "
                    f"--matrix-free --dtype float32)")
            if eligible:
                return cg_resident(a, b, tol=args.tol, rtol=args.rtol,
                                   maxiter=args.maxiter,
                                   check_every=args.check_every,
                                   m=m_res,
                                   record_history=(
                                       args.history
                                       or flight_cfg is not None),
                                   method=args.method,
                                   interpret=_pallas_interpret())
        if args.engine in ("auto", "streaming"):
            from .models.operators import _pallas_interpret
            from .solver.streaming import cg_streaming, streaming_eligible

            # same auto-only-on-TPU rule as the resident engine; the
            # shared streaming_eligible predicate is the authority
            # (one source of truth with solve(engine="streaming")).
            # Chebyshev rides the engine's fused cheb steps (round 5);
            # cheap gates first so the 30-matvec power iteration only
            # runs for solves that can actually take this path.
            from .solver.streaming import supports_streaming_op

            cheap_s = ((args.engine == "streaming"
                        or _jax_backend_is_tpu())
                       and args.precond in (None, "chebyshev")
                       and args.method == "cg"
                       and fault_plan is None
                       and supports_streaming_op(a))
            m_st = None
            if cheap_s and args.precond == "chebyshev":
                from .models.precond import ChebyshevPreconditioner

                m_st = ChebyshevPreconditioner.from_operator(
                    a, degree=args.precond_degree)
            eligible = cheap_s and streaming_eligible(
                a, b, m_st, method=args.method,
                record_history=args.history)
            if args.engine == "streaming" and not eligible:
                raise SystemExit(
                    f"--engine streaming does not support "
                    f"{type(a).__name__} at this size/dtype (needs a "
                    f"float32 2D/3D stencil satisfying the slab tiling, "
                    f"a float32 rhs, and --precond none or chebyshev; "
                    f"try --problem poisson3d --matrix-free)")
            if eligible:
                return cg_streaming(a, b, tol=args.tol, rtol=args.rtol,
                                    maxiter=args.maxiter,
                                    check_every=args.check_every,
                                    m=m_st,
                                    record_history=args.history,
                                    flight=flight_cfg,
                                    interpret=_pallas_interpret())
        from . import solve

        return solve(a, b, tol=args.tol, rtol=args.rtol,
                     maxiter=args.maxiter, m=_build_precond(),
                     record_history=args.history, method=args.method,
                     check_every=args.check_every, flight=flight_cfg,
                     fault=fault_plan)

    from .telemetry import events as tevents
    from .telemetry import session as tsession

    if args.mesh > 1:
        # the comm account below must come from THIS solve: other
        # distributed engines bypass dist_cg's cache, so a stale value
        # from an earlier solve in this process must not leak in
        from .parallel.dist_cg import reset_last_comm_cost
        from .telemetry.memscope import reset_last_memory_profile
        from .telemetry.shardscope import reset_last_shard_report

        reset_last_comm_cost()
        reset_last_shard_report()
        reset_last_memory_profile()

    # time_fn dispatches twice (compile warmup + timed); both really
    # happen, so both emit - the warmup's events labeled phase=warmup
    # for consumers that count per-solve selections or cache hits
    dispatches = [0]
    run_inner = run

    def run():  # noqa: F811 - deliberate wrap of the closure above
        dispatches[0] += 1
        if dispatches[0] == 1:
            with tevents.scoped(phase="warmup"):
                return run_inner()
        return run_inner()

    seq = None
    rseq = None
    with tsession.observe_solve(
            desc, engine=args.engine, check_every=args.check_every,
            profile_dir=args.profile, problem=args.problem,
            method=args.method, dtype=args.dtype,
            mesh=args.mesh,
            device=jax.devices()[0].platform) as obs:
        with obs.section("solve"):
            if args.checkpoint is not None:
                # the elastic resumable lane: dispatched ONCE (a
                # warmup re-dispatch would run the whole segmented
                # solve twice and delete the checkpoint under the
                # timed run), timed wall-clock around the loop
                import time as _time

                from .parallel import make_mesh as _mm
                from .robust import (
                    PreemptedError,
                    Preemption,
                    StragglerWatchdog,
                )
                from .utils.checkpoint import (
                    solve_resumable_distributed,
                )

                wd = None
                if args.watchdog is not None:
                    if args.watchdog <= 1.0:
                        raise SystemExit(
                            f"--watchdog THRESHOLD must be > 1 (a "
                            f"ratio), got {args.watchdog}")
                    wd = StragglerWatchdog(threshold=args.watchdog)
                t0 = _time.perf_counter()
                try:
                    result = solve_resumable_distributed(
                        a, b, args.checkpoint, mesh=_mm(args.mesh),
                        segment_iters=args.segment_iters,
                        tol=args.tol, rtol=args.rtol,
                        maxiter=args.maxiter,
                        preconditioner=args.precond,
                        plan=plan_obj, exchange=args.exchange,
                        elastic=args.elastic,
                        keep_last=args.keep_last, watchdog=wd,
                        inject=fault_plan,
                        check_every=args.check_every,
                        preempt=(Preemption(args.preempt_after)
                                 if args.preempt_after is not None
                                 else None),
                        # validated once pre-dispatch (or the user
                        # opted out) - same rule as every other lane
                        validate=False)
                except PreemptedError as e:
                    # the drill's expected exit: state is on disk,
                    # code 3 so scripts can branch on "resume me"
                    if args.json:
                        ulog.emit_json({
                            "status": "PREEMPTED",
                            "checkpoint": args.checkpoint,
                            "elastic": bool(args.elastic),
                            "detail": str(e)})
                    else:
                        print(f"status  : PREEMPTED ({e})")
                        print(f"resume  : re-run with --checkpoint "
                              f"{args.checkpoint}")
                    raise SystemExit(3)
                elapsed = _time.perf_counter() - t0
            elif args.recycle is not None:
                # the Krylov-recycling sequence: solve 1 harvests,
                # solves 2..N deflate and keep accumulating; the
                # reported record/timing is the FINAL (most-deflated)
                # solve's
                from .parallel import make_mesh as _mm
                from .solver.recycle import DEFAULT_K, recycled_sequence

                rseq = recycled_sequence(
                    a, b, mesh=_mm(args.mesh), repeats=args.repeat,
                    k=args.recycle or DEFAULT_K,
                    maxiter=args.maxiter, tol=args.tol,
                    rtol=args.rtol, preconditioner=args.precond,
                    precond_degree=args.precond_degree,
                    record_history=args.history,
                    check_every=args.check_every,
                    csr_comm=args.csr_comm, exchange=args.exchange,
                    plan=plan_obj,
                    # validated once pre-dispatch (same rule as the
                    # calibrate sequence)
                    validate=False)
                elapsed = rseq.entries[-1].elapsed_s
                result = rseq.result
            elif args.repeat > 1:
                # the calibrate-and-replan sequence loop: each solve is
                # warmup+timed inside solve_sequence (same protocol as
                # the time_fn below); the reported record/timing is the
                # FINAL solve's - the one running on the most-corrected
                # plan
                from .parallel import make_mesh, solve_sequence

                seq = solve_sequence(
                    a, b, mesh=make_mesh(args.mesh),
                    repeats=args.repeat, replan=args.replan,
                    plan=plan_obj, tol=args.tol, rtol=args.rtol,
                    maxiter=args.maxiter,
                    preconditioner=args.precond,
                    precond_degree=args.precond_degree,
                    record_history=args.history, method=args.method,
                    check_every=args.check_every,
                    csr_comm=args.csr_comm, flight=flight_cfg,
                    exchange=args.exchange,
                    # validated once pre-dispatch; a per-repeat O(nnz)
                    # host scan would distort the timed sequence
                    validate=False)
                elapsed, result = seq.final.elapsed_s, seq.final.result
                # downstream reporting (record/report/plan line) shows
                # the plan the final solve actually ran on
                plan_obj = seq.final.plan or plan_obj
            else:
                elapsed, result = time_fn(run, warmup=1, repeats=1)

        if args.df64:
            # adapt DF64CGResult to the CGResult-shaped reporting surface
            import types

            result = types.SimpleNamespace(
                x=result.x(), iterations=result.iterations,
                residual_norm=result.residual_norm(),
                converged=result.converged, indefinite=result.indefinite,
                status=result.status,
                status_enum=result.status_enum,
                # ||r|| with NaN fill - same semantics as CGResult, no
                # adaptation needed
                residual_history=result.residual_history,
                flight=result.flight)

        # Many-RHS solves: keep the CGBatchResult for per-lane
        # reporting and adapt an aggregate facade (worst lane) so the
        # scalar reporting surface below - record, events, report -
        # works unchanged.  iterations = the max lane (the loop ran
        # that many), status = the worst lane's code.
        many_result = None
        if args.rhs > 1:
            import types as _types

            from .solver.status import CGStatus as _CGS

            many_result = result
            _iters = np.asarray(result.iterations)
            _stat = np.asarray(result.status)
            worst = int(_stat.max())
            result = _types.SimpleNamespace(
                x=result.x,
                iterations=int(_iters.max()),
                residual_norm=float(
                    np.asarray(result.residual_norm).max()),
                converged=bool(np.asarray(result.converged).all()),
                status=worst,
                status_enum=lambda w=worst: _CGS(w),
                indefinite=bool(np.asarray(result.indefinite).any()),
                residual_history=None,
                flight=many_result.flight)

        # per-solve communication account: jaxpr-derived per-iteration
        # collective counts x the measured iteration count (the volume
        # that governs distributed SpMV scaling - see telemetry.cost)
        comm = None
        if args.mesh > 1:
            from .parallel.dist_cg import last_comm_cost

            info = last_comm_cost()
            if info is not None:
                sc, ctx = info
                totals = sc.totals(int(result.iterations))
                comm = {
                    "psum": totals.psum,
                    "ppermute": totals.ppermute,
                    "all_gather": totals.all_gather,
                    "comm_bytes": totals.comm_bytes,
                    "wire_bytes": totals.wire_bytes,
                    "per_iteration": sc.per_iteration.to_json(),
                    "setup": sc.setup.to_json(),
                    "kind": ctx.get("kind"),
                    "n_shards": ctx.get("n_shards"),
                }
                if ctx.get("exchange") is not None:
                    comm["exchange"] = ctx["exchange"]
                if ctx.get("halo_padding_fraction") is not None:
                    comm["halo_padding_fraction"] = \
                        ctx["halo_padding_fraction"]
        # The flight record: ONE host fetch of the solve-carried ring
        # buffer (the solve is complete and synced by now), then the
        # solve-health verdict computed host-side from the recorded
        # trace (telemetry.health) - classification + decay rates +
        # Ritz condition estimate, emitted as a solve_health event and
        # gauges by obs.finish.
        flight_rec = None
        health = None
        lane_records = None
        lane_healths = None
        if flight_cfg is not None and many_result is not None:
            from .telemetry.flight import lanes_from_buffer
            from .telemetry.health import assess_lanes

            if many_result.flight is not None:
                lane_records = lanes_from_buffer(
                    many_result.flight, args.rhs,
                    stride=flight_cfg.stride)
                lane_healths = assess_lanes(
                    lane_records, converged=many_result.converged,
                    statuses=many_result.status,
                    iterations=many_result.iterations)
                # the aggregate surface (report/--history/perfetto)
                # follows the slowest lane - the one that governed the
                # loop's runtime
                slow = int(np.asarray(many_result.iterations).argmax())
                flight_rec = lane_records[slow]
                health = lane_healths[slow]
        elif flight_cfg is not None:
            from .telemetry.flight import FlightRecord
            from .telemetry.health import assess_solve_health

            fbuf = getattr(result, "flight", None)
            if fbuf is not None:
                # ring buffers record at the configured stride; the
                # distributed resident engine's fbuf is its adapted
                # block trace (check_every-granular) - pass the known
                # stride rather than letting a 2-row trace infer it
                # from a cap-clamped final diff
                stride_hint = (max(1, args.check_every)
                               if args.engine == "resident"
                               else flight_cfg.stride)
                flight_rec = FlightRecord.from_buffer(
                    fbuf, stride=stride_hint)
            elif result.residual_history is not None:
                # engines whose recorder is the adapted dense/block
                # trace (single-device resident: record_history was
                # forced on above, check-block granular)
                flight_rec = FlightRecord.from_history(
                    result.residual_history,
                    stride=max(1, args.check_every))
            if flight_rec is not None and len(flight_rec):
                health = assess_solve_health(
                    flight_rec, converged=bool(result.converged),
                    status=int(result.status),
                    iterations=int(result.iterations))
        obs.finish(result, elapsed_s=elapsed, health=health,
                   **({"comm": comm} if comm is not None else {}))

        # Measured phase profiling (telemetry.phasetrace): its OWN
        # dispatches against the same partition the solve ran - the
        # solve's compiled body is untouched (jaxpr-identity proven in
        # tests/test_phasetrace.py).  Runs inside the solve's event
        # scope so the phase_profile event shares this solve_id (the
        # offline tools/solve_report.py fuses it back by that id).
        # One profiled solve yields the phase-resolved observations
        # that reach the lstsq2 confident calibration tier without
        # --repeat; the fit (with per-link wire bandwidths when the
        # gather lane ran) is persisted for future plans exactly like
        # a --repeat calibration.
        phase_profile_obj = None
        phase_fit = None
        if args.phase_profile is not None:
            from .parallel import make_mesh as _make_mesh
            from .telemetry import calibrate as _tcal2
            from .telemetry import phasetrace as _pt

            reps = args.phase_profile or _pt.DEFAULT_REPEATS
            with obs.section("phase-profile"):
                phase_profile_obj = _pt.profile_distributed(
                    a, mesh=_make_mesh(args.mesh), plan=plan_obj,
                    csr_comm=args.csr_comm, exchange=args.exchange,
                    repeats=reps,
                    solve_iterations=int(result.iterations),
                    solve_elapsed_s=float(elapsed))
                _pt.note_profile(phase_profile_obj)
            phase_fit = _tcal2.fit_machine_model(
                _tcal2.observations_from_profile(phase_profile_obj),
                per_link=phase_profile_obj.links)
            _tcal2.note_calibration(phase_fit)
            _tcal2.store_calibration(phase_fit)

    x_np = np.asarray(result.x)
    if rcm_perm is not None:  # scatter back to the original ordering
        x_orig = np.empty_like(x_np)
        x_orig[rcm_perm] = x_np
        x_np = x_orig

    record = ulog.solve_record(
        result, elapsed_s=elapsed, problem=desc, n=int(a.shape[0]),
        dtype=args.dtype, mesh=args.mesh,
        device=jax.devices()[0].platform,
        precond=args.precond or "none")
    if x_expected is not None:
        # many-RHS X_true was generated against the FINAL (post-rcm)
        # operator, so compare the un-scattered solution stack
        ref_x = np.asarray(result.x) if args.rhs > 1 else x_np
        err = float(np.max(np.abs(ref_x - np.asarray(x_expected))))
        record["max_abs_error"] = err
    if fault_plan is not None:
        record["fault"] = fault_plan.to_json()
    if recovery_box[0] is not None:
        record["recovery"] = recovery_box[0].to_json()
    if args.checkpoint is not None:
        from .telemetry.registry import REGISTRY as _REG

        mig_counter = _REG.snapshot().get("solve_migrations_total")
        migrations = 0
        if mig_counter:
            migrations = int(sum(
                s.get("value", 0)
                for s in mig_counter.get("series", [])))
        record["checkpoint"] = {
            "path": args.checkpoint,
            "segment_iters": args.segment_iters,
            "elastic": bool(args.elastic),
            "keep_last": args.keep_last,
            "watchdog_threshold": args.watchdog,
            "migrations": migrations,
        }
    if args.save_x:
        np.save(args.save_x,
                np.asarray(result.x) if args.rhs > 1 else x_np)
    if many_result is not None:
        # per-lane story: each column is a solve of its own, and the
        # record says so (the lint gate asserts per-lane errors)
        lanes = {
            "iterations": [int(v) for v in
                           np.asarray(many_result.iterations)],
            "residual_norm": [float(v) for v in
                              np.asarray(many_result.residual_norm)],
            "converged": [bool(v) for v in
                          np.asarray(many_result.converged)],
            "status": [s.name for s in many_result.status_enums()],
        }
        if x_expected is not None:
            diff = np.abs(np.asarray(many_result.x)
                          - np.asarray(x_expected))
            lanes["max_abs_error"] = [float(v)
                                      for v in diff.max(axis=0)]
        if lane_healths is not None:
            lanes["health"] = [h.classification.name
                               for h in lane_healths]
        record["n_rhs"] = args.rhs
        record["rhs_method"] = args.rhs_method
        if many_result.fallback is not None:
            record["rhs_fallback"] = bool(many_result.fallback)
        # aggregate useful work: converged lane-iterations per second -
        # the amortization number the bench row tracks
        record["rhs_iters_per_sec"] = \
            float(sum(lanes["iterations"])) / max(elapsed, 1e-30)
        record["lanes"] = ulog.sanitize(lanes)
    if comm is not None:
        record["comm"] = comm
    if plan_obj is not None:
        plan_entry = {
            "label": plan_obj.label,
            "reorder": plan_obj.reorder,
            "split": plan_obj.split,
            "exchange": plan_obj.exchange,
            "objective": plan_obj.objective,
            "fingerprint": plan_obj.fingerprint(),
            "score": float(plan_obj.score),
        }
        if plan_obj.baseline_imbalance:
            plan_entry["even_imbalance"] = plan_obj.baseline_imbalance
        if plan_obj.report is not None:
            plan_entry["predicted_imbalance"] = \
                plan_obj.report.imbalance()
        from .telemetry.shardscope import last_shard_report as _lsr

        shard_rep_now = _lsr()
        if shard_rep_now is not None:
            # the schedule-specific accounting of the partition that
            # actually ran (only computed when telemetry is active)
            plan_entry["measured_imbalance"] = shard_rep_now.imbalance()
        record["plan"] = ulog.sanitize(plan_entry)
    # Runtime calibration & drift (telemetry.calibrate): the sequence
    # summary when --repeat ran; a single planned distributed solve
    # still gets its predicted-vs-measured drift tracked against the
    # model that scored its plan.  Host-side fusion only - the solve is
    # already complete and synced.
    if rseq is not None:
        record["recycle"] = ulog.sanitize(rseq.summary())
    calib_entry = None
    if seq is not None:
        calib_entry = ulog.sanitize(seq.summary())
    elif args.mesh > 1 and plan_obj is not None \
            and plan_obj.report is not None:
        from .balance.plan import reference_model
        from .telemetry import calibrate as tcal

        drift_item = {"float64": 8, "df64": 8, "bfloat16": 2}.get(
            args.dtype, 4)
        # price drift with the model that SCORED the plan (the drift
        # contract): a FILE-loaded plan records its scorer by name, so
        # recover it from the calibration cache when it is this host's
        # calibrated model; otherwise the reference table is the
        # honest fallback and DriftReport.model says so
        drift_model = plan_model
        if drift_model is None \
                and plan_obj.scored_by != "reference-tpu-v5e":
            pref = tcal.preferred_model()
            if pref is not None and pref.name == plan_obj.scored_by:
                drift_model = pref
        dr = tcal.note_drift(
            tcal.drift_report(plan_obj.report, int(result.iterations),
                              float(elapsed), itemsize=drift_item,
                              model=drift_model or reference_model(),
                              plan=plan_obj),
            report=plan_obj.report, plan=plan_obj)
        calib_entry = ulog.sanitize({"drift": dr.to_json()})
    if calib_entry is not None:
        record["calibration"] = calib_entry
    if phase_profile_obj is not None:
        record["phase_profile"] = ulog.sanitize({
            **phase_profile_obj.to_json(),
            "calibration": phase_fit.to_json(),
        })
    if flight_rec is not None:
        record["flight"] = flight_rec.summary()
    if health is not None:
        record["health"] = health.to_json()
    if args.metrics and args.json:
        from .telemetry.registry import REGISTRY

        record["metrics"] = REGISTRY.snapshot()

    # The unified solve report + Perfetto timeline (telemetry.report):
    # all host-side fusion of already-synced aggregates - the solve
    # itself is untouched (TestZeroPerturbation covers this path).
    mem_payload = None
    if args.memory_report or args.report is not None \
            or args.trace_perfetto:
        from .telemetry.memscope import last_memory_profile

        mem_prof = last_memory_profile()
        if mem_prof is not None:
            mem_payload = dict(mem_prof["footprint"].to_json())
            if mem_prof.get("measured_bytes") is not None:
                mem_payload["measured_bytes"] = \
                    int(mem_prof["measured_bytes"])
            if mem_prof.get("device_peak_bytes") is not None:
                mem_payload["device_peak_bytes"] = \
                    int(mem_prof["device_peak_bytes"])
    if args.memory_report and args.json:
        record["memory"] = mem_payload
    solve_report = None
    if args.report is not None or args.trace_perfetto:
        from .telemetry import report as treport
        from .telemetry import roofline as troofline
        from .telemetry.shardscope import last_shard_report

        shard_rep = last_shard_report() if args.mesh > 1 else None
        # the roofline's communication term prices the real
        # interconnect bytes (wire semantics - an all_gather lands
        # (P-1) blocks per device, not its input aval)
        comm_bpi = (comm["per_iteration"].get(
            "wire_bytes", comm["per_iteration"]["comm_bytes"])
            if comm is not None else 0.0)
        itemsize = {"float64": 8, "df64": 8, "bfloat16": 2}.get(
            args.dtype, 4)
        roof = troofline.analyze(
            n=int(a.shape[0]), nnz=troofline.operator_nnz(a),
            itemsize=itemsize, iterations=int(result.iterations),
            elapsed_s=float(elapsed),
            method=args.rhs_method if args.rhs > 1 else args.method,
            preconditioned=args.precond is not None,
            precond_matvecs=(args.precond_degree - 1
                             if args.precond == "chebyshev" else 0),
            comm_bytes_per_iteration=comm_bpi,
            n_rhs=args.rhs)
        solve_report = treport.SolveReport(
            record=record, shard=shard_rep, roofline=roof,
            flight_summary=record.get("flight"),
            health=record.get("health"),
            comm=comm, calibration=calib_entry,
            phase=record.get("phase_profile"),
            memory=mem_payload,
            sections=tuple(obs.timer.sections))
        if args.report is not None and args.report != "-":
            with open(args.report, "w", encoding="utf-8") as f:
                f.write(solve_report.to_text())
        if args.json and args.report is not None:
            record["solve_report"] = solve_report.to_json()
        if args.trace_perfetto:
            hist = None
            if flight_rec is not None:
                hist = flight_rec.to_history(args.maxiter)
            elif result.residual_history is not None:
                hist = result.residual_history
            trace = treport.perfetto_trace(
                iterations=int(result.iterations),
                elapsed_s=float(elapsed), shard=shard_rep,
                n_shards=args.mesh,
                sections=tuple(obs.timer.sections),
                flight_history=hist,
                phase_profile=phase_profile_obj, label=desc)
            treport.write_perfetto(args.trace_perfetto, trace)

    if args.json:
        ulog.emit_json(record)
    else:
        print(f"problem : {desc}")
        print(f"device  : {record['device']} (mesh={args.mesh}), "
              f"dtype={args.dtype}")
        print(f"status  : {record['status']} "
              f"({result.status_enum().describe()})")
        print(f"iters   : {record['iterations']}")
        print(f"||r||   : {record['residual_norm']:.6e}")
        print(f"time    : {elapsed * 1e3:.3f} ms "
              f"({record['iters_per_sec']:.1f} iters/s)")
        if many_result is not None:
            lanes = record["lanes"]
            print(f"rhs     : {args.rhs} lanes ({args.rhs_method}"
                  f"{', fell back to batched' if record.get('rhs_fallback') else ''}), "
                  f"{record['rhs_iters_per_sec']:.1f} aggregate "
                  f"lane-iters/s")
            print(f"  lane iters  : {lanes['iterations']}")
            print(f"  lane status : {lanes['status']}")
        if "max_abs_error" in record:
            print(f"max err : {record['max_abs_error']:.3e}")
        if fault_plan is not None:
            print(f"fault   : {fault_plan.describe()}")
        if recovery_box[0] is not None:
            rr = recovery_box[0]
            print(f"recover : {rr.attempts} attempt(s), "
                  f"{rr.restarts} restart(s), "
                  f"{'recovered' if rr.recovered else 'NOT recovered'}"
                  f" ({len(rr.faults)} fault(s) detected)")
        if args.checkpoint is not None:
            ckr = record["checkpoint"]
            print(f"elastic : checkpoint {ckr['path']} "
                  f"(segment {ckr['segment_iters']} iters, keep_last "
                  f"{ckr['keep_last']}, {ckr['migrations']} "
                  f"migration(s) this process)")
        # The reference prints the full solution vector (CUDACG.cu:361-364);
        # keep that behavior for small systems.
        if a.shape[0] <= 10 and args.rhs == 1:
            for v in x_np:
                print(f"{v:f}")
        if comm is not None:
            ex_note = ""
            if comm.get("exchange"):
                ex_note = f", exchange={comm['exchange']}"
                pad_frac = comm.get("halo_padding_fraction")
                if pad_frac is not None:
                    ex_note += f" (halo padding {pad_frac * 100:.1f}%)"
            print(f"comm    : {comm['psum']} psum, "
                  f"{comm['ppermute']} ppermute, "
                  f"{comm['all_gather']} all_gather, "
                  f"{comm['comm_bytes']} payload bytes "
                  f"(per-device; {comm['per_iteration']['comm_bytes']} "
                  f"payload + "
                  f"{comm['per_iteration'].get('wire_bytes', 0)} wire "
                  f"bytes/iter{ex_note})")
        if plan_obj is not None:
            pe = record["plan"]
            imb = pe.get("measured_imbalance") \
                or pe.get("predicted_imbalance") or {}
            even = pe.get("even_imbalance") or {}
            detail = ""
            if imb and even:
                detail = (f" (nnz max/mean "
                          f"{even['nnz_max_over_mean']:.2f} -> "
                          f"{imb['nnz_max_over_mean']:.2f})")
            print(f"plan    : {pe['label']} [{pe['fingerprint']}]"
                  f"{detail}")
        if seq is not None:
            for line in seq.describe_lines():
                print(line)
        if rseq is not None:
            for line in rseq.describe_lines():
                print(f"recycle : {line}")
        if phase_profile_obj is not None:
            from .telemetry.report import phase_lines as _phase_lines

            for line in _phase_lines(record["phase_profile"]):
                print(f"phase   : {line}")
            print(f"phase   : calibration {phase_fit.describe()}")
        if args.memory_report:
            if mem_payload is not None:
                from .telemetry.report import memory_lines as _mem_lines

                for line in _mem_lines(mem_payload):
                    print(f"memory  : {line}")
            else:
                print("memory  : no distributed memory profile (the "
                      "memscope account needs --mesh > 1)")
        if health is not None:
            print(f"health  : {health.classification.name}: "
                  f"{health.message}")
        if args.history:
            hist_src = result
            every = max(1, int(result.iterations) // 20)
            dense_missing = result.residual_history is None
            if flight_rec is not None \
                    and (dense_missing or args.engine == "resident"):
                # engines with no dense trace print the recorder's
                # stride-decimated one through the same formatter, and
                # the resident engines' dense-layout trace is
                # check-block granular (finite only at multiples of
                # check_every): either way the print stride must be a
                # multiple of the recorder's or the sampled indices
                # land on NaN (unrecorded) rows and the trace collapses
                # to almost nothing
                s = max(1, int(flight_rec.stride))
                every = max(s, every // s * s)
                if dense_missing:
                    import types as _types

                    hist_src = _types.SimpleNamespace(
                        residual_history=flight_rec.to_history(
                            args.maxiter),
                        iterations=result.iterations)
            print(ulog.format_history(hist_src, every=every))
        if args.metrics:
            # THE ops-plane formatter (serve.ops.prometheus_exposition):
            # the one-shot dump is byte-identical to a /metrics scrape
            from .serve.ops import prometheus_exposition

            print("--- metrics (prometheus text) ---")
            print(prometheus_exposition(), end="")
        if solve_report is not None and args.report == "-":
            print()
            print(solve_report.to_text(), end="")
    return 0 if bool(result.converged) else 1


if __name__ == "__main__":
    sys.exit(main())
