"""cuda_mpi_parallel_tpu: a TPU-native sparse linear-solver framework.

A ground-up rebuild of the capabilities of the reference
``Yan12345678/CUDA-MPI-parallel`` (a single-file cuSPARSE/cuBLAS conjugate-
gradient solver, ``CUDACG.cu``) designed for TPU hardware: Pallas/XLA SpMV
over HBM-resident operators, a ``lax.while_loop``-jitted solver body with
on-device convergence checks, and row-partitioned multi-chip execution where
per-iteration inner products become ``lax.psum`` over the ICI mesh and the
distributed SpMV halo exchange uses ``lax.ppermute``.

Public API surface::

    from cuda_mpi_parallel_tpu import cg, solve, CGStatus
    from cuda_mpi_parallel_tpu import (CSRMatrix, ELLMatrix, DenseOperator,
                                       Stencil2D, Stencil3D,
                                       JacobiPreconditioner)
    from cuda_mpi_parallel_tpu.models import poisson, random_spd
"""

from .models.operators import (
    CSRMatrix,
    DenseOperator,
    ELLMatrix,
    IdentityOperator,
    JacobiPreconditioner,
    LinearOperator,
    ShiftELLMatrix,
    Stencil2D,
    Stencil3D,
)
from .solver.cg import CGCheckpoint, CGResult, cg, solve
from .solver.df64 import DF64CGResult, DF64Checkpoint, cg_df64
from .solver.resident import (
    cg_resident,
    cg_resident_df64,
    supports_resident,
    supports_resident_df64,
)
from .solver.status import CGStatus
from .solver.streaming import (
    cg_streaming,
    cg_streaming_df64,
    supports_streaming_df64,
    supports_streaming_op,
)
from .balance import PartitionPlan, plan_partition

__version__ = "0.1.0"

__all__ = [
    "CGCheckpoint",
    "CGResult",
    "CGStatus",
    "CSRMatrix",
    "DF64CGResult",
    "DF64Checkpoint",
    "DenseOperator",
    "ELLMatrix",
    "IdentityOperator",
    "JacobiPreconditioner",
    "LinearOperator",
    "PartitionPlan",
    "ShiftELLMatrix",
    "Stencil2D",
    "Stencil3D",
    "cg",
    "plan_partition",
    "cg_df64",
    "cg_resident",
    "cg_resident_df64",
    "cg_streaming",
    "cg_streaming_df64",
    "solve",
    "supports_resident",
    "supports_resident_df64",
    "supports_streaming_df64",
    "supports_streaming_op",
]
