"""SPD-preserving symmetric reorderings for partition planning.

A contiguous row split (``nnz_split``) balances *work*; the *halo* a
shard exchanges is set by how many of its matrix columns live on other
shards, which is a property of the ORDERING.  Symmetric permutations
``P A P^T`` preserve symmetry and positive-definiteness exactly (the
spectrum is invariant), so the solver sees the same conditioning while
the partition sees a matrix whose couplings are concentrated near the
diagonal - cross-shard references collapse to the shards' boundary
neighborhoods, which is the node-aware-SpMV result (arXiv 1612.08060):
balanced rows plus bandwidth-reducing order is what converts a measured
stall factor into recovered wall time.

Two orderings, both returning ``perm[new] = old`` (the convention of
``CSRMatrix.permuted`` / ``native.bindings.rcm_order``):

* ``rcm_reorder`` - reverse Cuthill-McKee, delegating to the operator's
  native C++/scipy path.  The classic bandwidth reducer; after it, a
  contiguous split's cross-shard columns shrink to O(bandwidth) per
  boundary.
* ``greedy_nnz_reorder`` - a greedy envelope-reduction variant that is
  *nnz-aware*: grow the ordering one row at a time, always appending
  the unordered row with the most already-ordered neighbors
  (maximizing locality of the coupling), breaking ties toward lighter
  rows so heavy rows spread through the order instead of clumping at a
  BFS frontier the splitter then has to cut through.  Component seeds
  are min-degree rows (the RCM heuristic).

Host-side numpy/heapq; O(nnz log n).
"""
from __future__ import annotations

import heapq

import numpy as np

__all__ = [
    "greedy_nnz_reorder",
    "inverse_permutation",
    "rcm_reorder",
]


def inverse_permutation(perm: np.ndarray) -> np.ndarray:
    """``inv`` with ``inv[perm[i]] = i``: maps an old index to its new
    position.  ``x_original = x_permuted[inv]`` undoes a solve in the
    permuted ordering (``CSRMatrix.permuted`` docstring)."""
    perm = np.asarray(perm)
    inv = np.empty(perm.shape[0], dtype=np.int64)
    inv[perm] = np.arange(perm.shape[0], dtype=np.int64)
    return inv


def rcm_reorder(a) -> np.ndarray:
    """Reverse Cuthill-McKee via the operator's own native/scipy path."""
    return np.asarray(a.rcm_permutation(), dtype=np.int64)


def greedy_nnz_reorder(a) -> np.ndarray:
    """Greedy max-adjacency, light-rows-first envelope ordering.

    At every step append the unordered row with the most neighbors
    already ordered; among equals prefer the row with fewer total
    entries.  Seeds (per connected component) are min-degree rows.
    A lazy-deletion heap keeps it O(nnz log n) - stale heap entries
    are skipped when their recorded adjacency no longer matches.
    """
    indptr = np.asarray(a.indptr, dtype=np.int64)
    indices = np.asarray(a.indices, dtype=np.int64)
    n = int(indptr.shape[0]) - 1
    degree = indptr[1:] - indptr[:-1]
    placed = np.zeros(n, dtype=bool)
    adjacency = np.zeros(n, dtype=np.int64)  # ordered-neighbor count
    order = np.empty(n, dtype=np.int64)
    heap: list = []
    seed_order = np.argsort(degree, kind="stable")
    seed_pos = 0
    count = 0
    while count < n:
        while heap:
            neg_adj, deg, row = heapq.heappop(heap)
            if not placed[row] and -neg_adj == adjacency[row]:
                break
        else:
            # heap empty (or all stale): seed the next component with
            # the lightest unplaced row
            while placed[seed_order[seed_pos]]:
                seed_pos += 1
            row = int(seed_order[seed_pos])
        placed[row] = True
        order[count] = row
        count += 1
        for nb in indices[indptr[row]:indptr[row + 1]]:
            nb = int(nb)
            if nb == row or placed[nb]:
                continue
            adjacency[nb] += 1
            heapq.heappush(heap,
                           (-int(adjacency[nb]), int(degree[nb]), nb))
    return order
