"""balance: imbalance-aware partition planning.

The feedback loop the ROADMAP's "imbalance-aware repartitioning" item
asked for: ``telemetry.shardscope`` measures per-shard nnz/halo skew at
partition time; this package feeds the measurement BACK into how the
partition is cut, so skewed unstructured systems stop stalling every
``psum`` behind their heaviest shard.

* :mod:`.nnz_split` - contiguous balanced-nnz row splitting (exact
  chains-on-chains bottleneck via prefix-sum probing + boundary
  refinement), variable real rows per shard under the partitioners'
  common padded slot count;
* :mod:`.reorder` - SPD-preserving symmetric permutations (RCM
  bandwidth reduction; a greedy nnz-aware envelope ordering) that
  shrink the cross-shard coupling a contiguous cut has to pay;
* :mod:`.plan` - :class:`PartitionPlan` and :func:`plan_partition`,
  which enumerates (reorder x split) candidates, scores each with
  shardscope's static accounting joined to the roofline comm model,
  and returns the minimizer.

Consumption: ``solve_distributed(..., plan="auto" | PartitionPlan)``
and ``solve_distributed_df64(..., plan=...)`` thread a plan through
the CSR partitioners (``parallel.partition`` honors
``row_ranges=``), key the compiled-solver cache on the plan
fingerprint, and scatter the solution back through the inverse
permutation; ``plan=None`` is bit-identical to the legacy even split.
All host-side numpy - a plan never touches device state.
"""
from .nnz_split import balanced_nnz_ranges, even_ranges, validate_ranges
from .plan import (
    GREEDY_REORDER_LIMIT,
    PartitionPlan,
    plan_partition,
    reference_model,
    score_report,
)
from .reorder import (
    greedy_nnz_reorder,
    inverse_permutation,
    rcm_reorder,
)

__all__ = [
    "GREEDY_REORDER_LIMIT",
    "PartitionPlan",
    "balanced_nnz_ranges",
    "even_ranges",
    "greedy_nnz_reorder",
    "inverse_permutation",
    "plan_partition",
    "rcm_reorder",
    "reference_model",
    "score_report",
    "validate_ranges",
]
