"""Contiguous balanced-nnz row splitting (the chains-on-chains problem).

The even row split every partitioner shipped with assigns ``ceil(n/P)``
rows per shard regardless of how the nonzeros fall.  On a skewed
unstructured system that is exactly the ``nnz_max_over_mean`` stall
factor shardscope measures: a psum-synchronized loop runs at the speed
of the heaviest shard, every iteration (Bienz et al., arXiv 1612.08060
SS3; Kreutzer et al., arXiv 1112.5588 SS4 make the same observation for
GPU clusters).  This module fixes the *split* half of the problem:
assign each shard a CONTIGUOUS run of rows whose nnz totals are as
equal as the row granularity allows.

Contiguity is not a simplification - it is what keeps the distributed
schedules intact.  Every partitioner in ``parallel.partition`` maps
"shard s owns rows [lo, hi)" onto its collective schedule (block
all_gather, ring x-block rotation); an arbitrary row assignment would
need a gather/scatter layer per matvec.  Contiguous balanced splitting
is the classic chains-on-chains partitioning problem (CCP: place P-1
dividers in a chain of weighted tasks minimizing the max chain weight),
solved here exactly:

* ``balanced_nnz_ranges`` - prefix-sum probe for the optimal bottleneck
  (binary search on the max-shard-nnz value; each feasibility probe is
  a greedy ``searchsorted`` walk over the nnz prefix sums, O(P log n)),
  then a local boundary refinement pass that spreads rows back across
  underfull trailing shards (the greedy walk front-loads) without ever
  increasing the bottleneck;
* ``even_ranges`` - the legacy split as a range tuple, so planners and
  reports can compare the two through one code path.

Variable rows per shard compose with ``shard_map``'s uniform-shape
constraint through padding, not ragged shapes: the partitioners pad
every shard to the max real row count with unit-diagonal rows (see
``parallel.partition``), so a balanced split trades a few padding rows
for the removal of the nnz stall factor.

Host-side numpy only; nothing here touches device state.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = [
    "balanced_nnz_ranges",
    "even_ranges",
    "range_nnz",
    "validate_ranges",
]

Ranges = Tuple[Tuple[int, int], ...]


def even_ranges(n: int, n_shards: int) -> Ranges:
    """The legacy even row split as ``((lo, hi), ...)`` ranges.

    Matches ``partition.partition_csr``'s default layout exactly:
    ``ceil(n / P)`` rows per shard, trailing shards short (possibly
    empty when ``P > n``)."""
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    n_local = -(-n // n_shards) if n else 0
    return tuple(
        (min(s * n_local, n), min((s + 1) * n_local, n))
        for s in range(n_shards))


def range_nnz(indptr: np.ndarray, ranges: Ranges) -> np.ndarray:
    """Live matrix entries per range, straight off the CSR indptr."""
    c = np.asarray(indptr, dtype=np.int64)
    return np.array([int(c[hi] - c[lo]) for lo, hi in ranges],
                    dtype=np.int64)


def validate_ranges(ranges, n: int, n_shards: int) -> Ranges:
    """Check that ``ranges`` is a contiguous cover of ``[0, n)`` with one
    (possibly empty) range per shard; returns the normalized tuple."""
    ranges = tuple((int(lo), int(hi)) for lo, hi in ranges)
    if len(ranges) != n_shards:
        raise ValueError(
            f"expected {n_shards} row ranges, got {len(ranges)}")
    cursor = 0
    for k, (lo, hi) in enumerate(ranges):
        if lo != cursor or hi < lo:
            raise ValueError(
                f"row ranges must tile [0, {n}) contiguously; range {k} "
                f"is [{lo}, {hi}) after covering [0, {cursor})")
        cursor = hi
    if cursor != n:
        raise ValueError(
            f"row ranges cover [0, {cursor}), expected [0, {n})")
    return ranges


def _feasible(c: np.ndarray, n: int, n_shards: int, bottleneck: int,
              max_local_rows: Optional[int]) -> bool:
    """Can P greedy chains each holding <= ``bottleneck`` nnz (and
    optionally <= ``max_local_rows`` rows) cover all n rows?"""
    start = 0
    for _ in range(n_shards):
        if start >= n:
            return True
        end = int(np.searchsorted(c, c[start] + bottleneck,
                                  side="right")) - 1
        if max_local_rows is not None:
            end = min(end, start + max_local_rows)
        if end <= start:
            return False  # a single row exceeds the probe bottleneck
        start = end
    return start >= n


def _greedy_boundaries(c: np.ndarray, n: int, n_shards: int,
                       bottleneck: int,
                       max_local_rows: Optional[int]) -> np.ndarray:
    bounds = np.zeros(n_shards + 1, dtype=np.int64)
    start = 0
    for s in range(n_shards):
        if start < n:
            end = int(np.searchsorted(c, c[start] + bottleneck,
                                      side="right")) - 1
            if max_local_rows is not None:
                end = min(end, start + max_local_rows)
            end = max(end, start + 1)
            start = min(end, n)
        bounds[s + 1] = start
    bounds[n_shards] = n
    return bounds


def _refine_boundaries(c: np.ndarray, bounds: np.ndarray,
                       max_local_rows: Optional[int]) -> np.ndarray:
    """Local divider refinement: slide each internal boundary while it
    strictly improves ``(max nnz, max rows)`` of the two adjacent
    chains.  The greedy walk that seeded ``bounds`` front-loads shards
    (trailing shards can come out empty); this pass spreads rows back
    without ever increasing the global bottleneck - each move is
    accepted only if the local pairwise maximum decreases, and the
    global max over shards is the max of those pairwise maxima."""
    bounds = bounds.copy()
    n_shards = len(bounds) - 1

    def cost(lo, mid, hi):
        left = (int(c[mid] - c[lo]), mid - lo)
        right = (int(c[hi] - c[mid]), hi - mid)
        return max(left, right)

    for _ in range(2 * n_shards):
        moved = False
        for s in range(1, n_shards):
            lo, mid, hi = int(bounds[s - 1]), int(bounds[s]), \
                int(bounds[s + 1])
            best_mid, best_cost = mid, cost(lo, mid, hi)
            for cand in (mid - 1, mid + 1):
                if cand < lo or cand > hi:
                    continue
                if max_local_rows is not None and (
                        cand - lo > max_local_rows
                        or hi - cand > max_local_rows):
                    continue
                cc = cost(lo, cand, hi)
                if cc < best_cost:
                    best_mid, best_cost = cand, cc
            if best_mid != mid:
                bounds[s] = best_mid
                moved = True
        if not moved:
            break
    return bounds


def balanced_nnz_ranges(indptr, n_shards: int, *,
                        max_local_rows: Optional[int] = None) -> Ranges:
    """Contiguous row ranges minimizing the max per-shard nnz.

    Args:
      indptr: CSR row-pointer array of the GLOBAL matrix (n + 1 long).
      n_shards: number of contiguous chains to cut.
      max_local_rows: optional cap on real rows per shard.  The padded
        local size every shard allocates is ``max_s (hi_s - lo_s)``
        (``shard_map`` wants uniform shapes), so an uncapped split of a
        matrix with a dense block plus a long light tail can hand one
        shard most of the ROWS and inflate everyone's padding; the cap
        bounds that trade.  When the cap makes the instance infeasible
        (``P * cap < n``) it is ignored.

    Returns:
      ``((lo_0, hi_0), ..., (lo_{P-1}, hi_{P-1}))`` tiling ``[0, n)``.
      The bottleneck (max per-shard nnz) is exactly optimal among
      contiguous splits for the given cap; the refinement pass then
      evens out rows at equal bottleneck.
    """
    c = np.asarray(indptr, dtype=np.int64)
    n = int(c.shape[0]) - 1
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    if n <= 0 or n_shards == 1:
        return validate_ranges(even_ranges(n, n_shards), n, n_shards)
    if max_local_rows is not None and max_local_rows * n_shards < n:
        max_local_rows = None  # cap infeasible: ignore, keep covering
    total = int(c[n])
    row_nnz_max = int(np.max(c[1:] - c[:-1]))
    lo_b = max(row_nnz_max, -(-total // n_shards))
    hi_b = total
    # binary search the optimal bottleneck; the row cap can make a
    # bottleneck infeasible that pure nnz would accept, so probe with
    # both constraints applied
    while lo_b < hi_b:
        mid = (lo_b + hi_b) // 2
        if _feasible(c, n, n_shards, mid, max_local_rows):
            hi_b = mid
        else:
            lo_b = mid + 1
    bounds = _greedy_boundaries(c, n, n_shards, lo_b, max_local_rows)
    bounds = _refine_boundaries(c, bounds, max_local_rows)
    ranges = tuple((int(bounds[s]), int(bounds[s + 1]))
                   for s in range(n_shards))
    return validate_ranges(ranges, n, n_shards)
