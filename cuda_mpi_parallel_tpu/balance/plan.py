"""Partition planning: choose (reorder x split x exchange) by predicted
stall cost.

``telemetry.shardscope`` can *measure* per-shard nnz/halo skew the
moment a partition is built; this module closes the loop by choosing
the partition FROM that measurement before anything is built.  A
:func:`plan_partition` call enumerates candidate plans - a symmetric
SPD-preserving reordering (none / RCM / greedy nnz-aware, see
``.reorder``) crossed with a contiguous row split (even / balanced-nnz,
see ``.nnz_split``) crossed with a halo-exchange lane (allgather /
gather, see ``parallel.exchange``) - scores each candidate with
shardscope's static accounting (``report_for_ranges``) joined to the
roofline communication model (``telemetry.roofline.MachineModel``),
and returns the minimizer as a :class:`PartitionPlan`.

The default score is the modeled per-iteration SHARD-STALL time of the
shipped distributed schedules.  Under ``shard_map`` every shard is
padded to identical shapes, so nnz skew does not make one device late -
it inflates the UNIFORM padded slot count every device multiplies
through (that is how the ``nnz_max_over_mean`` stall factor is paid
here), plus the wire term of the candidate's exchange lane:

    score =   slots_max * (itemsize + 4) * G / mem_bw    (padded work)
            + wire_bytes(exchange) / net_bw              (halo wire)

    wire_bytes(allgather | ring) = (P - 1) * n_local * itemsize
    wire_bytes(gather)           = padded coupled-entry rounds
                                   (shardscope.gather_wire_bytes)

``G`` (``model.gather_slowdown``) prices sparse-gather work against
the streaming bandwidth the machine model quotes: the per-entry x
gather is random access, measured 1-2 orders slower per element than a
streamed read on the repo's own benches (``ops.pallas.spmv``
docstring: shift-ELL beats the CSR gather ~20-1000x); the table
default of 8 (:data:`GATHER_SLOWDOWN`) is a deliberately conservative
charge.

Balancing nnz shrinks the first term; keeping shards row-compact (the
``row_cap_factor`` cap) bounds the allgather wire; a bandwidth-
reducing reorder shrinks the gather wire.  Since PR 7 the coupled
halo is priced at FULL weight on the gather lane - the wire honors it
now (``parallel.exchange`` ships exactly the coupled entries), so the
historical one-quarter down-weight fudge is gone: each lane is charged the
bytes its schedule actually moves.  All three machine parameters (mem
bandwidth, net bandwidth, gather slowdown) live on ONE
``telemetry.roofline.MachineModel`` shared with the roofline and the
runtime calibrator; the default is the deterministic TPU-class
reference table (:func:`reference_model`) so plans stay
host-independent, and a runtime-calibrated model
(``telemetry.calibrate``) is used only when explicitly passed via
``model=`` (``dist_cg.resolve_plan`` does this for sequences).

Everything is host-side numpy over the CSR structure arrays - no
device state, no tracing; a plan is pure layout metadata that the
``parallel`` partitioners consume (``row_ranges=``) and the solvers
invert on the way out (``permutation``).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional, Sequence, Tuple

import numpy as np

from . import nnz_split, reorder as reorder_mod

__all__ = [
    "GREEDY_REORDER_LIMIT",
    "PartitionPlan",
    "plan_partition",
    "reference_model",
    "score_report",
    "wire_bytes_for",
]

#: rows above which the O(nnz log n) Python-heap greedy ordering is
#: dropped from the candidate set (RCM's native path stays; planning a
#: multi-million-row system should not spend minutes in heapq)
GREEDY_REORDER_LIMIT = 200_000

_REFERENCE = [None]


def __getattr__(name):
    # GATHER_SLOWDOWN is a lazy alias of the ONE shared definition
    # (telemetry.roofline.DEFAULT_GATHER_SLOWDOWN, also the
    # MachineModel field default) - duplicating the literal here let
    # the two layers this PR unified drift apart; lazy so importing
    # balance/ alone stays light (roofline pulls the telemetry stack)
    if name == "GATHER_SLOWDOWN":
        from ..telemetry.roofline import DEFAULT_GATHER_SLOWDOWN

        return DEFAULT_GATHER_SLOWDOWN
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def reference_model():
    """The planner's deterministic reference machine: the roofline
    TPU-class table plus the conservative gather-slowdown default, as
    one shared ``telemetry.roofline.MachineModel``.  Only the ratios
    matter for ranking candidates, and defaulting to a calibrated
    per-host model would make plans host-dependent - so this is the
    default, and calibrated models are opt-in via ``model=``."""
    if _REFERENCE[0] is None:
        from ..telemetry.roofline import MachineModel

        # gather_slowdown deliberately omitted: the MachineModel field
        # default IS the shared table value
        _REFERENCE[0] = MachineModel(
            name="reference-tpu-v5e", mem_bytes_per_s=8.19e11,
            flops_per_s=2.0e13, net_bytes_per_s=4.5e10,
            hbm_bytes=16.0 * 2 ** 30, source="table")
    return _REFERENCE[0]


@dataclasses.dataclass(frozen=True, eq=False)
class PartitionPlan:
    """One chosen partition layout: how to reorder, where to cut.

    ``row_ranges`` and ``report`` describe the matrix AFTER
    ``permutation`` is applied (``perm[new] = old``, the
    ``CSRMatrix.permuted`` convention); ``permutation is None`` means
    the original ordering.  ``report`` is the PREDICTED ShardReport
    (coupling-based halo semantics, ``report_for_ranges``); the
    schedule-specific measured report is emitted by the partitioner at
    solve time and the two ride one ``partition_plan`` telemetry event.
    """

    n_shards: int
    row_ranges: Tuple[Tuple[int, int], ...]
    permutation: Optional[np.ndarray]   # perm[new] = old, or None
    reorder: str                        # "none" | "rcm" | "greedy"
    split: str                          # "even" | "nnz"
    objective: str
    score: float
    #: the halo-exchange lane this plan was scored for: "allgather"
    #: (the legacy fixed collective - also what a pre-exchange saved
    #: plan loads as), "gather" (packed coupled-entry ppermute rounds,
    #: parallel.exchange) or "ring" (full x-block rotation).  The
    #: solve honors it unless the caller pins exchange= explicitly.
    exchange: str = "allgather"
    report: Optional[object] = None     # predicted ShardReport
    #: the even-split imbalance digest of the UNpermuted matrix - the
    #: baseline the plan is beating, for reports and benches
    baseline_imbalance: Optional[dict] = None
    #: name of the MachineModel whose parameters priced ``score`` -
    #: "reference-tpu-v5e" unless a calibrated model was passed; the
    #: proof hook for "solve k+1 ran on a runtime-corrected plan"
    scored_by: str = "reference-tpu-v5e"

    @property
    def label(self) -> str:
        # the legacy allgather lane keeps the historical two-part label
        # (dashboards and gauge series keyed on it stay continuous);
        # other lanes name their wire
        if self.exchange == "allgather":
            return f"{self.reorder}+{self.split}"
        return f"{self.reorder}+{self.split}+{self.exchange}"

    def fingerprint(self) -> str:
        """Short stable digest of the layout (ranges + permutation +
        exchange lane): the solver-cache key component and event
        correlation id.  The legacy allgather lane hashes exactly as
        before this field existed, so saved pre-exchange plans keep
        their recorded fingerprints."""
        h = hashlib.sha1()
        h.update(repr((self.n_shards, self.row_ranges)).encode())
        if self.permutation is not None:
            h.update(np.ascontiguousarray(
                self.permutation, dtype=np.int64).tobytes())
        if self.exchange != "allgather":
            h.update(f"exchange={self.exchange}".encode())
        return h.hexdigest()[:12]

    def inverse_permutation(self) -> Optional[np.ndarray]:
        if self.permutation is None:
            return None
        return reorder_mod.inverse_permutation(self.permutation)

    @property
    def n_global(self) -> int:
        return int(self.row_ranges[-1][1]) if self.row_ranges else 0

    def validate_for(self, a) -> None:
        n = int(a.shape[0])
        if self.n_global != n:
            raise ValueError(
                f"plan covers {self.n_global} rows but the operator has "
                f"{n} (plan fingerprints are per-matrix layouts)")
        if self.permutation is not None:
            # full bijection check, not just length: a corrupt saved
            # plan must be rejected HERE (downstream gathers clamp
            # out-of-range indices and would return a silently wrong x)
            if self.permutation.shape[0] != n or not np.array_equal(
                    np.sort(self.permutation), np.arange(n)):
                raise ValueError(
                    f"plan permutation is not a permutation of "
                    f"range({n})")

    def is_trivial(self) -> bool:
        """True when the plan IS the legacy layout: no permutation,
        the even row split, and a fixed-payload wire (allgather/ring -
        what the unplanned schedules run anyway).  ``resolve_plan``
        collapses trivial plans to ``None`` so an auto-planned solve
        of an already-balanced system shares the unplanned executable
        (same cache key, same jaxpr) instead of compiling a
        byte-identical twin.  A gather-lane plan is never trivial: its
        wire differs from the legacy schedule even on even ranges."""
        return self.permutation is None and self.exchange != "gather" \
            and self.row_ranges \
            == nnz_split.even_ranges(self.n_global, self.n_shards)

    def describe(self) -> str:
        pred = ""
        if self.report is not None and self.baseline_imbalance:
            pred = (f", nnz max/mean "
                    f"{self.baseline_imbalance['nnz_max_over_mean']:.2f}"
                    f" -> "
                    f"{self.report.imbalance()['nnz_max_over_mean']:.2f}")
        return (f"{self.label} over {self.n_shards} shards "
                f"({self.fingerprint()}{pred})")

    def to_json(self) -> dict:
        return {
            "version": 1,
            "n_shards": self.n_shards,
            "row_ranges": [[int(lo), int(hi)]
                           for lo, hi in self.row_ranges],
            "permutation": (None if self.permutation is None
                            else [int(v) for v in self.permutation]),
            "reorder": self.reorder,
            "split": self.split,
            "exchange": self.exchange,
            "objective": self.objective,
            "score": float(self.score),
            "fingerprint": self.fingerprint(),
            "predicted": (None if self.report is None
                          else self.report.to_json()),
            "baseline_imbalance": self.baseline_imbalance,
            "scored_by": self.scored_by,
        }

    @classmethod
    def from_json(cls, data: dict) -> "PartitionPlan":
        from ..telemetry.shardscope import ShardReport

        perm = data.get("permutation")
        pred = data.get("predicted")
        return cls(
            n_shards=int(data["n_shards"]),
            row_ranges=tuple((int(lo), int(hi))
                             for lo, hi in data["row_ranges"]),
            permutation=(None if perm is None
                         else np.asarray(perm, dtype=np.int64)),
            reorder=str(data.get("reorder", "?")),
            split=str(data.get("split", "?")),
            # pre-exchange saved plans were scored for (and ran) the
            # allgather wire - load them as exactly that
            exchange=str(data.get("exchange", "allgather")),
            objective=str(data.get("objective", "auto")),
            score=float(data.get("score", 0.0)),
            report=(None if pred is None
                    else ShardReport.from_json(pred)),
            baseline_imbalance=data.get("baseline_imbalance"),
            scored_by=str(data.get("scored_by", "reference-tpu-v5e")),
        )

    def layout_json(self) -> dict:
        """MINIMAL layout identity - exactly what a distributed
        checkpoint must record to be migratable to a different mesh
        shape later (``robust.elastic``): the row ranges, the
        permutation, the exchange lane and the fingerprint.  No
        predicted report, no score - a checkpoint's npz should not
        carry a planner diagnostic payload."""
        return {
            "n_shards": int(self.n_shards),
            "row_ranges": [[int(lo), int(hi)]
                           for lo, hi in self.row_ranges],
            "permutation": (None if self.permutation is None
                            else [int(v) for v in self.permutation]),
            "exchange": self.exchange,
            "fingerprint": self.fingerprint(),
            "label": self.label,
        }

    @classmethod
    def from_layout_json(cls, data: dict) -> "PartitionPlan":
        """Rebuild a plan from its :meth:`layout_json` - enough to lift
        a checkpoint's padded plan-permuted state back to global row
        order (reorder/split/score are unknown and labeled so)."""
        perm = data.get("permutation")
        return cls(
            n_shards=int(data["n_shards"]),
            row_ranges=tuple((int(lo), int(hi))
                             for lo, hi in data["row_ranges"]),
            permutation=(None if perm is None
                         else np.asarray(perm, dtype=np.int64)),
            reorder="saved", split="saved", objective="saved",
            score=0.0,
            exchange=str(data.get("exchange", "allgather")),
        )

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_json(), f)

    @classmethod
    def load(cls, path: str) -> "PartitionPlan":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_json(json.load(f))


def wire_bytes_for(report, exchange: str, itemsize: int) -> float:
    """Per-device per-matvec interconnect bytes of ``exchange`` on the
    layout ``report`` describes (coupling semantics,
    ``shardscope.report_for_ranges``).

    The fixed lanes (allgather / ring) land ``(P - 1) * n_local``
    entries on every device however the entries couple; the gather
    lane ships the coupled-entry rounds padded per-round to the max
    over shards (``shardscope.gather_wire_bytes`` - FULL weight, no
    down-weighting: since ``parallel.exchange`` the wire honors the
    coupling, so the planner charges exactly what is sent)."""
    if exchange == "gather":
        from ..telemetry.shardscope import gather_wire_bytes

        return float(gather_wire_bytes(report))
    from ..parallel.exchange import allgather_wire_bytes

    # one definition of the dense wire, shared with choose_exchange's
    # auto rule - refining the all_gather pricing updates both at once
    return float(allgather_wire_bytes(report.n_shards, report.n_local,
                                      itemsize))


def score_report(report, *, objective: str = "time", itemsize: int = 8,
                 model=None, exchange: str = "allgather") -> float:
    """Rank a candidate layout; lower is better (seconds for 'time').

    ``report`` is a coupling-semantics ``ShardReport``
    (``shardscope.report_for_ranges``); ``model`` a
    ``telemetry.roofline.MachineModel`` supplying the mem/net
    bandwidths and gather slowdown (default: :func:`reference_model`);
    ``exchange`` the halo wire the candidate would run (its bytes are
    priced via :func:`wire_bytes_for`).  Public because the drift
    tracker (``telemetry.calibrate``) and the replan loop
    (``dist_cg.solve_sequence``) re-price already-built layouts with
    the same terms the planner used to choose them."""
    if objective == "nnz":
        from ..telemetry.shardscope import max_over_mean

        return float(max_over_mean(report.nnz))
    if objective == "halo":
        return float(report.halo_send_bytes.max()
                     + report.halo_recv_bytes.max())
    if model is None:
        model = reference_model()
    from ..telemetry.roofline import DEFAULT_GATHER_SLOWDOWN

    mem_bps = float(model.mem_bytes_per_s)
    net_bps = float(model.net_bytes_per_s
                    or reference_model().net_bytes_per_s)
    gather = float(getattr(model, "gather_slowdown",
                           DEFAULT_GATHER_SLOWDOWN))
    # "time": modeled per-iteration stall seconds (module docstring)
    slot_term = (float(report.slots.max()) * (itemsize + 4)
                 * gather / mem_bps)
    wire_term = wire_bytes_for(report, exchange, itemsize) / net_bps
    return slot_term + wire_term


def plan_partition(a, n_shards: int, *, objective: str = "auto",
                   reorders: Optional[Sequence[str]] = None,
                   splits: Sequence[str] = ("even", "nnz"),
                   exchange: str = "auto",
                   row_cap_factor: float = 1.25,
                   itemsize: Optional[int] = None,
                   model=None,
                   hbm_budget: Optional[float] = None) -> PartitionPlan:
    """Enumerate (reorder x split x exchange) candidates; return the
    minimizer.

    Args:
      a: the global assembled ``CSRMatrix`` (SPD; symmetric pattern).
      n_shards: mesh size the partition targets.
      objective: ``"auto"``/``"time"`` (modeled per-iteration stall
        seconds - the default), ``"nnz"`` (pure nnz max/mean stall
        factor) or ``"halo"`` (peak coupling bytes).
      reorders: candidate orderings; default ``("none", "rcm",
        "greedy")`` with greedy dropped past
        :data:`GREEDY_REORDER_LIMIT` rows.
      splits: candidate row splits (``"even"``, ``"nnz"``).
      exchange: halo-wire lanes to search - ``"auto"`` (the default)
        scores every (reorder, split) under BOTH the legacy allgather
        wire and the coupled-entry gather wire
        (``parallel.exchange``), full weight each, and lets the
        cheaper lane win; ``"allgather"``/``"gather"``/``"ring"`` pin
        one lane (ring prices like allgather: the rotation lands the
        same fixed payload).
      row_cap_factor: balanced-nnz splits cap real rows per shard at
        ``ceil(n/P) * factor`` so one shard of very light rows cannot
        inflate everyone's padded local size (see
        ``nnz_split.balanced_nnz_ranges``).
      itemsize: value bytes for halo/slot pricing (default: the
        matrix dtype's).
      model: a ``telemetry.roofline.MachineModel`` to price the time
        objective against (mem/net bandwidth AND gather slowdown);
        default is the static TPU-class reference table
        (:func:`reference_model`) so plans are host-deterministic.
        Pass a ``telemetry.calibrate`` runtime-fitted model to rank
        against measured behavior - the plan's ``scored_by`` records
        which model chose it.
      hbm_budget: per-device HBM bytes the chosen partition must fit
        in (``telemetry.memscope`` accounting: worst-shard pinned
        partition bytes + the modeled solver working set).  Candidates
        that overflow are dropped from the search; when EVERY layout
        overflows at ``n_shards``, the planner doubles the mesh until
        one fits (a tight budget drives the shard count up) and the
        returned plan's ``n_shards`` records the grown size.  When no
        mesh up to ``n`` rows fits, raises
        :class:`telemetry.memscope.MemoryBudgetError` naming the
        bytes.  ``None`` (default) skips the gate entirely.

    Returns:
      The best :class:`PartitionPlan`; candidates are tried simplest
      first (none+even leads), so on a balanced structured system the
      planner returns the legacy layout and the solve proceeds exactly
      as an unplanned one would.
    """
    if objective == "auto":
        objective = "time"
    if objective not in ("time", "nnz", "halo"):
        raise ValueError(f"unknown plan objective {objective!r}")
    if exchange not in ("auto", "allgather", "gather", "ring"):
        raise ValueError(f"unknown plan exchange {exchange!r}")
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    # nnz/halo objectives rank layouts, not wires: score once per
    # (reorder, split) on the pinned lane (or the legacy default)
    if exchange != "auto":
        lanes = (exchange,)
    elif objective == "time":
        lanes = ("allgather", "gather")
    else:
        lanes = ("allgather",)
    from ..telemetry import shardscope

    n = int(a.shape[0])
    if itemsize is None:
        itemsize = int(np.asarray(a.data).dtype.itemsize)
    if model is None:
        model = reference_model()
    if reorders is None:
        reorders = ("none", "rcm", "greedy")
        if n > GREEDY_REORDER_LIMIT:
            reorders = ("none", "rcm")
    row_cap = max(1, int(-(-n // n_shards) * row_cap_factor)) \
        if row_cap_factor else None

    baseline = shardscope.report_for_ranges(
        a, nnz_split.even_ranges(n, n_shards), itemsize=itemsize,
        plan="none+even")
    baseline_imb = baseline.imbalance()

    def _fits_budget(rep, lane) -> bool:
        # worst-shard persistent bytes (exact slot accounting from the
        # predicted report + the modeled solver working set) vs the
        # per-device budget; the gather lane's extended-x buffer holds
        # the halo rows the report predicts
        if hbm_budget is None:
            return True
        from ..telemetry import memscope

        halo_w = 0
        if lane == "gather":
            halo_w = int(np.ceil(
                float(np.asarray(rep.halo_recv_bytes).max()) / itemsize))
        solver = memscope.solver_bytes_per_shard(
            n_local=rep.n_local, n_shards=n_shards, itemsize=itemsize,
            exchange=lane, halo_width=halo_w)
        worst = int(np.asarray(rep.persistent_bytes).max()) + solver
        return worst <= hbm_budget

    over_budget = 0
    best = None
    for rname in reorders:
        if rname == "none":
            perm, ap = None, a
        elif rname == "rcm":
            perm = reorder_mod.rcm_reorder(a)
            ap = a.permuted(perm)
        elif rname == "greedy":
            perm = reorder_mod.greedy_nnz_reorder(a)
            ap = a.permuted(perm)
        else:
            raise ValueError(f"unknown reorder {rname!r}")
        indptr = np.asarray(ap.indptr)
        for sname in splits:
            if sname == "even":
                ranges = nnz_split.even_ranges(n, n_shards)
            elif sname == "nnz":
                ranges = nnz_split.balanced_nnz_ranges(
                    indptr, n_shards, max_local_rows=row_cap)
            else:
                raise ValueError(f"unknown split {sname!r}")
            if rname == "none" and sname == "even":
                rep = baseline  # same inputs; the O(nnz) walk is paid once
            else:
                rep = shardscope.report_for_ranges(
                    ap, ranges, itemsize=itemsize,
                    plan=f"{rname}+{sname}")
            trivial_layout = rname == "none" and sname == "even"
            for lane in lanes:
                if not _fits_budget(rep, lane):
                    over_budget += 1
                    continue
                score = score_report(rep, objective=objective,
                                     itemsize=itemsize, model=model,
                                     exchange=lane)
                cand = PartitionPlan(
                    n_shards=n_shards, row_ranges=ranges,
                    permutation=perm,
                    reorder=rname, split=sname, objective=objective,
                    score=score, exchange=lane, report=rep,
                    baseline_imbalance=baseline_imb,
                    scored_by=str(model.name))
                if best is None:
                    best = cand               # none+even on the FIRST
                    legacy_score = score      # lane: the legacy lane
                    layout_floor = score
                    continue
                # Two-layer hysteresis (candidate order runs simplest
                # first: trivial layout leads, allgather lane before
                # gather, so ties always stay with the simpler choice):
                if trivial_layout:
                    # a wire upgrade on the legacy LAYOUT carries no
                    # permutation/variable-row churn but still compiles
                    # a new executable - it must clear the same > 2%
                    # bar vs the legacy lane
                    if score < legacy_score * 0.98 \
                            and score < best.score * (1 - 1e-9):
                        best = cand
                    layout_floor = min(layout_floor, score)
                    continue
                # a LAYOUT deviation must beat the best trivial-layout
                # lane by > 2%: reordering to collect a wire win the
                # trivial layout already gets for free is pure churn
                # for a model-noise-sized gain
                if score < layout_floor * 0.98 \
                        and score < best.score * (1 - 1e-9):
                    best = cand
    if best is None:
        if over_budget:
            # every layout overflows this mesh: grow it (doubling keeps
            # pod-slice shapes) until one fits, or refuse with the
            # memscope accounting once shards would outnumber rows
            if n_shards * 2 <= n:
                return plan_partition(
                    a, n_shards * 2, objective=objective,
                    reorders=reorders, splits=splits,
                    exchange=exchange, row_cap_factor=row_cap_factor,
                    itemsize=itemsize, model=model,
                    hbm_budget=hbm_budget)
            from ..telemetry import memscope

            required = int(np.asarray(
                baseline.persistent_bytes).max()) \
                + memscope.solver_bytes_per_shard(
                    n_local=baseline.n_local, n_shards=n_shards,
                    itemsize=itemsize, exchange="allgather")
            raise memscope.MemoryBudgetError(
                f"no partition of this {n}-row system fits "
                f"hbm_budget={int(hbm_budget)} bytes at any mesh size "
                f"up to {n_shards} shards (worst-shard persistent "
                f"bytes {required} at {n_shards} shards)",
                required_bytes=required,
                budget_bytes=int(hbm_budget), n_shards=n_shards)
        raise ValueError(
            "plan_partition needs at least one (reorder, split) "
            "candidate; got empty reorders/splits")
    return best
